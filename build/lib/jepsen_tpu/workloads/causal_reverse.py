"""Causal-reverse probe: strict-serializability anomaly where T2 is
visible without an earlier T1.

Equivalent of /root/reference/jepsen/src/jepsen/tests/causal_reverse.clj:
concurrent blind writes of distinct integers per key, with transactional
reads of the key's full set.  Replaying the history, every write w_i
records the set of writes already acknowledged before w_i was invoked;
any read that observes w_i must also observe that set (:20-74).
"""

from __future__ import annotations

import threading
from typing import Any, Optional

from .. import client as jc
from ..checker.core import Checker
from ..generator.core import limit, mix, stagger
from ..generator.independent import concurrent_generator
from ..history import OK, History
from ..parallel.independent import KV, independent_checker


def precedence_graph(history: History) -> dict:
    """{written-value: frozenset(values acked before its invocation)}
    (causal_reverse.clj:21-48)."""
    completed: set = set()
    expected: dict[Any, frozenset] = {}
    for op in history:
        if op.f != "write":
            continue
        if op.is_invoke:
            expected[op.value] = frozenset(completed)
        elif op.is_ok:
            completed.add(op.value)
    return expected


def errors(history: History, expected: dict) -> list[dict]:
    """Reads that observe a write without its predecessors
    (causal_reverse.clj:50-74)."""
    out = []
    for op in history:
        if not (op.is_ok and op.f == "read"):
            continue
        seen = set(op.value or [])
        must: set = set()
        for v in seen:
            must |= expected.get(v, frozenset())
        missing = must - seen
        if missing:
            out.append({
                "op-index": op.index,
                "process": op.process,
                "missing": sorted(missing),
                "expected-count": len(must),
            })
    return out


class CausalReverseChecker(Checker):
    def check(self, test: dict, history: History, opts: dict) -> dict:
        expected = precedence_graph(history)
        errs = errors(history, expected)
        return {"valid": not errs, "errors": errs[:32],
                "error-count": len(errs)}


class InMemoryListClient(jc.Client):
    """Per-key insert-only list with atomic snapshot reads."""

    def __init__(self, state=None, lock=None):
        self.state = state if state is not None else {}
        self.lock = lock or threading.Lock()

    def open(self, test, node):
        return InMemoryListClient(self.state, self.lock)

    def invoke(self, test, op):
        k, v = op.value.key, op.value.value
        with self.lock:
            lst = self.state.setdefault(k, [])
            if op.f == "write":
                lst.append(v)
                return op.complete(OK)
            return op.complete(OK, value=KV(k, list(lst)))

    def reusable(self, test):
        return True


def generator(opts: dict):
    """Mixed reads + unique-value writes per key, n workers per key
    (causal_reverse.clj:76-114)."""
    n = max(1, len(opts.get("nodes") or ["n1"]))
    per_key = opts.get("per-key-limit", 500)

    def fgen(k):
        counter = iter(range(10**9))

        def write():
            return {"f": "write", "value": next(counter)}

        return limit(
            per_key,
            stagger(0.01, mix([{"f": "read", "value": None},
                               write])),
        )

    return concurrent_generator(n, range(1_000_000), fgen)


def workload(opts: Optional[dict] = None) -> dict:
    opts = opts or {}
    return {
        "name": "causal-reverse",
        "generator": generator(opts),
        "checker": independent_checker(CausalReverseChecker()),
        "client": InMemoryListClient(),
    }
