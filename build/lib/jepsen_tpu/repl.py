"""Interactive exploration namespace (repl.clj's role): one import
that brings the whole toolkit into scope for a REPL session.

    >>> from jepsen_tpu.repl import *
    >>> t = store.load(store.latest())
    >>> h = History(list(t.iter_ops()))
    >>> checker.linearizable(models.cas_register()).check({}, h, {})
"""

from jepsen_tpu import (  # noqa: F401
    checker,
    cli,
    client,
    codec,
    core,
    db,
    faketime,
    fs_cache,
    generator,
    lazyfs,
    models,
    nemesis,
    net,
    oses,
    reconnect,
    report,
    store,
    web,
)
from jepsen_tpu.control import (  # noqa: F401
    DummyRemote,
    LocalRemote,
    Session,
    SshCliRemote,
    on_nodes,
    with_sessions,
)
from jepsen_tpu.history import History, Op, history  # noqa: F401
from jepsen_tpu.parallel.independent import (  # noqa: F401
    KV,
    independent_checker,
)
