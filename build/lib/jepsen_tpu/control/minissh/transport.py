"""SSH-2 binary packet protocol + curve25519-sha256 key exchange.

RFC 4253 (transport), RFC 8731 (curve25519 kex), RFC 8709
(ssh-ed25519).  One ciphersuite: aes128-ctr + hmac-sha2-256, no
compression, no rekeying.  Both client and server sides live here; the
asymmetry is confined to `Transport.handshake`.
"""

from __future__ import annotations

import hashlib
import hmac as hmac_mod
import os
import socket
import struct
import threading

from cryptography.hazmat.primitives.asymmetric.ed25519 import (
    Ed25519PrivateKey,
    Ed25519PublicKey,
)
from cryptography.hazmat.primitives.asymmetric.x25519 import (
    X25519PrivateKey,
    X25519PublicKey,
)
from cryptography.hazmat.primitives.ciphers import Cipher, algorithms, modes

VERSION = b"SSH-2.0-jepsen_tpu_minissh_0.1"

# message numbers (RFC 4253 / 4252 / 4254)
MSG_DISCONNECT = 1
MSG_IGNORE = 2
MSG_UNIMPLEMENTED = 3
MSG_DEBUG = 4
MSG_SERVICE_REQUEST = 5
MSG_SERVICE_ACCEPT = 6
MSG_KEXINIT = 20
MSG_NEWKEYS = 21
MSG_KEX_ECDH_INIT = 30
MSG_KEX_ECDH_REPLY = 31
MSG_USERAUTH_REQUEST = 50
MSG_USERAUTH_FAILURE = 51
MSG_USERAUTH_SUCCESS = 52
MSG_USERAUTH_PK_OK = 60
MSG_GLOBAL_REQUEST = 80
MSG_REQUEST_SUCCESS = 81
MSG_REQUEST_FAILURE = 82
MSG_CHANNEL_OPEN = 90
MSG_CHANNEL_OPEN_CONFIRMATION = 91
MSG_CHANNEL_OPEN_FAILURE = 92
MSG_CHANNEL_WINDOW_ADJUST = 93
MSG_CHANNEL_DATA = 94
MSG_CHANNEL_EXTENDED_DATA = 95
MSG_CHANNEL_EOF = 96
MSG_CHANNEL_CLOSE = 97
MSG_CHANNEL_REQUEST = 98
MSG_CHANNEL_SUCCESS = 99
MSG_CHANNEL_FAILURE = 100

KEX_ALGO = b"curve25519-sha256"
HOSTKEY_ALGO = b"ssh-ed25519"
CIPHER = b"aes128-ctr"
MAC = b"hmac-sha2-256"


class SshError(Exception):
    pass


# ------------------------------------------------------------ wire encoding


def u32(x: int) -> bytes:
    return struct.pack(">I", x)


def sstr(b: bytes) -> bytes:
    return u32(len(b)) + b


def mpint(x: int) -> bytes:
    if x == 0:
        return u32(0)
    b = x.to_bytes((x.bit_length() + 7) // 8, "big")
    if b[0] & 0x80:  # positive numbers need a leading zero bit
        b = b"\x00" + b
    return sstr(b)


class Buf:
    """Sequential reader over a packet payload."""

    __slots__ = ("b", "i")

    def __init__(self, b: bytes):
        self.b = b
        self.i = 0

    def byte(self) -> int:
        self.i += 1
        return self.b[self.i - 1]

    def bool(self) -> bool:
        return self.byte() != 0

    def u32(self) -> int:
        v = struct.unpack_from(">I", self.b, self.i)[0]
        self.i += 4
        return v

    def string(self) -> bytes:
        n = self.u32()
        s = self.b[self.i:self.i + n]
        if len(s) != n:
            raise SshError("truncated string")
        self.i += n
        return s

    def rest(self) -> bytes:
        return self.b[self.i:]


# ------------------------------------------------------------- host keys


def hostkey_blob(pub: Ed25519PublicKey) -> bytes:
    from cryptography.hazmat.primitives.serialization import (
        Encoding,
        PublicFormat,
    )

    raw = pub.public_bytes(Encoding.Raw, PublicFormat.Raw)
    return sstr(HOSTKEY_ALGO) + sstr(raw)


def pub_from_blob(blob: bytes) -> Ed25519PublicKey:
    buf = Buf(blob)
    algo = buf.string()
    if algo != HOSTKEY_ALGO:
        raise SshError(f"unsupported key algo {algo!r}")
    return Ed25519PublicKey.from_public_bytes(buf.string())


def sig_blob(sig: bytes) -> bytes:
    return sstr(HOSTKEY_ALGO) + sstr(sig)


def sig_from_blob(blob: bytes) -> bytes:
    buf = Buf(blob)
    if buf.string() != HOSTKEY_ALGO:
        raise SshError("unsupported signature algo")
    return buf.string()


# ------------------------------------------------------------- transport


def _kexinit_payload() -> bytes:
    nl = sstr  # name-list == string of comma-joined names
    return (
        bytes([MSG_KEXINIT])
        + os.urandom(16)
        + nl(KEX_ALGO)
        + nl(HOSTKEY_ALGO)
        + nl(CIPHER)      # ciphers c->s
        + nl(CIPHER)      # ciphers s->c
        + nl(MAC)         # macs c->s
        + nl(MAC)         # macs s->c
        + nl(b"none")     # compression c->s
        + nl(b"none")     # compression s->c
        + nl(b"")         # languages c->s
        + nl(b"")         # languages s->c
        + b"\x00"         # first_kex_packet_follows
        + u32(0)          # reserved
    )


def _check_kexinit(payload: bytes) -> None:
    buf = Buf(payload)
    if buf.byte() != MSG_KEXINIT:
        raise SshError("expected KEXINIT")
    buf.i += 16  # cookie
    lists = [buf.string() for _ in range(10)]
    wanted = [KEX_ALGO, HOSTKEY_ALGO, CIPHER, CIPHER, MAC, MAC,
              b"none", b"none"]
    for want, got in zip(wanted, lists):
        names = got.split(b",")
        if want not in names:
            raise SshError(
                f"no common algorithm: need {want!r} in {got!r}"
            )


class Transport:
    """One SSH connection's packet layer, after `handshake()` runs the
    version exchange + kex + (for clients) the caller does userauth."""

    def __init__(self, sock: socket.socket, *, server_side: bool,
                 host_key: Ed25519PrivateKey | None = None):
        self.sock = sock
        self.server_side = server_side
        self.host_key = host_key
        self._rbuf = b""
        self._wlock = threading.Lock()  # exec pumps write concurrently
        self._seq_in = 0
        self._seq_out = 0
        self._enc = None   # outgoing cipher ctx
        self._dec = None   # incoming cipher ctx
        self._mac_out = b""
        self._mac_in = b""
        self.session_id: bytes | None = None

    # -- raw socket helpers ------------------------------------------------

    def _recv_exact(self, n: int) -> bytes:
        while len(self._rbuf) < n:
            chunk = self.sock.recv(65536)
            if not chunk:
                raise SshError("connection closed")
            self._rbuf += chunk
        out, self._rbuf = self._rbuf[:n], self._rbuf[n:]
        return out

    def _recv_line(self) -> bytes:
        while b"\n" not in self._rbuf:
            chunk = self.sock.recv(4096)
            if not chunk:
                raise SshError("connection closed in version exchange")
            self._rbuf += chunk
        line, self._rbuf = self._rbuf.split(b"\n", 1)
        return line.rstrip(b"\r")

    # -- packets -----------------------------------------------------------

    def write_packet(self, payload: bytes) -> None:
        block = 16 if self._enc else 8
        # packet_length(4) + padding_length(1) + payload + padding ≡ 0
        # (mod block); padding ≥ 4.
        pad = block - ((5 + len(payload)) % block)
        if pad < 4:
            pad += block
        pkt = u32(1 + len(payload) + pad) + bytes([pad]) + payload \
            + os.urandom(pad)
        with self._wlock:
            if self._enc:
                mac = hmac_mod.new(
                    self._mac_out, u32(self._seq_out) + pkt, hashlib.sha256
                ).digest()
                pkt = self._enc.update(pkt) + mac
            self.sock.sendall(pkt)
            self._seq_out = (self._seq_out + 1) & 0xFFFFFFFF

    def read_packet(self) -> bytes:
        if self._dec:
            first = self._dec.update(self._recv_exact(16))
            plen = struct.unpack(">I", first[:4])[0]
            if plen > 1 << 24:
                raise SshError(f"packet too large: {plen}")
            rest = self._dec.update(self._recv_exact(plen - 12))
            mac = self._recv_exact(32)
            pkt = first + rest
            want = hmac_mod.new(
                self._mac_in, u32(self._seq_in) + pkt, hashlib.sha256
            ).digest()
            if not hmac_mod.compare_digest(mac, want):
                raise SshError("bad MAC")
        else:
            first = self._recv_exact(4)
            plen = struct.unpack(">I", first)[0]
            if plen > 1 << 24:
                raise SshError(f"packet too large: {plen}")
            pkt = first + self._recv_exact(plen)
        self._seq_in = (self._seq_in + 1) & 0xFFFFFFFF
        pad = pkt[4]
        # pkt = len(4) + padlen(1) + payload + padding
        payload = pkt[5:4 + struct.unpack(">I", pkt[:4])[0] - pad]
        return payload

    def readable(self, timeout: float = 0.0) -> bool:
        """True when a read_message() call would find bytes to start
        on.  Used instead of socket timeouts: a timeout raised halfway
        through an encrypted packet would desynchronize the CTR
        keystream, so callers must only invoke read_message when
        committed to blocking for the whole packet."""
        if self._rbuf:
            return True
        import select

        r, _, _ = select.select([self.sock], [], [], timeout)
        return bool(r)

    def read_message(self) -> bytes:
        """read_packet, transparently dropping IGNORE/DEBUG."""
        while True:
            p = self.read_packet()
            if not p:
                continue
            if p[0] in (MSG_IGNORE, MSG_DEBUG, MSG_UNIMPLEMENTED):
                continue
            if p[0] == MSG_DISCONNECT:
                buf = Buf(p)
                buf.byte()
                code = buf.u32()
                msg = buf.string()
                raise SshError(f"disconnected ({code}): {msg.decode(errors='replace')}")
            return p

    # -- key exchange ------------------------------------------------------

    def handshake(self) -> None:
        # version exchange
        self.sock.sendall(VERSION + b"\r\n")
        peer = self._recv_line()
        while not peer.startswith(b"SSH-"):
            peer = self._recv_line()  # pre-banner lines are allowed
        if not peer.startswith(b"SSH-2.0-"):
            raise SshError(f"unsupported peer version {peer!r}")
        v_c = peer if self.server_side else VERSION
        v_s = VERSION if self.server_side else peer

        my_kexinit = _kexinit_payload()
        self.write_packet(my_kexinit)
        peer_kexinit = self.read_message()
        _check_kexinit(peer_kexinit)
        i_c = peer_kexinit if self.server_side else my_kexinit
        i_s = my_kexinit if self.server_side else peer_kexinit

        eph = X25519PrivateKey.generate()
        from cryptography.hazmat.primitives.serialization import (
            Encoding,
            PublicFormat,
        )

        my_q = eph.public_key().public_bytes(Encoding.Raw, PublicFormat.Raw)

        if self.server_side:
            pkt = self.read_message()
            buf = Buf(pkt)
            if buf.byte() != MSG_KEX_ECDH_INIT:
                raise SshError("expected KEX_ECDH_INIT")
            q_c = buf.string()
            shared = eph.exchange(X25519PublicKey.from_public_bytes(q_c))
            k_s = hostkey_blob(self.host_key.public_key())
            h = self._exchange_hash(v_c, v_s, i_c, i_s, k_s, q_c, my_q,
                                    shared)
            sig = self.host_key.sign(h)
            self.write_packet(
                bytes([MSG_KEX_ECDH_REPLY])
                + sstr(k_s) + sstr(my_q) + sstr(sig_blob(sig))
            )
            q_s = my_q
        else:
            self.write_packet(bytes([MSG_KEX_ECDH_INIT]) + sstr(my_q))
            pkt = self.read_message()
            buf = Buf(pkt)
            if buf.byte() != MSG_KEX_ECDH_REPLY:
                raise SshError("expected KEX_ECDH_REPLY")
            k_s = buf.string()
            q_s = buf.string()
            sig = sig_from_blob(buf.string())
            shared = eph.exchange(X25519PublicKey.from_public_bytes(q_s))
            h = self._exchange_hash(v_c, v_s, i_c, i_s, k_s, my_q, q_s,
                                    shared)
            # Like StrictHostKeyChecking=no (the mode SshCliRemote
            # passes): verify the signature proves possession of the
            # presented key, but accept any host key.
            pub_from_blob(k_s).verify(sig, h)

        if self.session_id is None:
            self.session_id = h
        self.write_packet(bytes([MSG_NEWKEYS]))
        if self.read_message() != bytes([MSG_NEWKEYS]):
            raise SshError("expected NEWKEYS")
        self._activate_keys(shared, h)

    def _exchange_hash(self, v_c, v_s, i_c, i_s, k_s, q_c, q_s,
                       shared: bytes) -> bytes:
        k = int.from_bytes(shared, "big")
        blob = (
            sstr(v_c) + sstr(v_s) + sstr(i_c) + sstr(i_s)
            + sstr(k_s) + sstr(q_c) + sstr(q_s) + mpint(k)
        )
        return hashlib.sha256(blob).digest()

    def _derive(self, shared: bytes, h: bytes, letter: bytes,
                size: int) -> bytes:
        k = mpint(int.from_bytes(shared, "big"))
        out = hashlib.sha256(k + h + letter + self.session_id).digest()
        while len(out) < size:
            out += hashlib.sha256(k + h + out).digest()
        return out[:size]

    def _activate_keys(self, shared: bytes, h: bytes) -> None:
        iv_c = self._derive(shared, h, b"A", 16)
        iv_s = self._derive(shared, h, b"B", 16)
        key_c = self._derive(shared, h, b"C", 16)
        key_s = self._derive(shared, h, b"D", 16)
        mac_c = self._derive(shared, h, b"E", 32)
        mac_s = self._derive(shared, h, b"F", 32)

        def ctr(key, iv):
            return Cipher(algorithms.AES(key), modes.CTR(iv))

        if self.server_side:
            self._dec = ctr(key_c, iv_c).decryptor()
            self._enc = ctr(key_s, iv_s).encryptor()
            self._mac_in, self._mac_out = mac_c, mac_s
        else:
            self._enc = ctr(key_c, iv_c).encryptor()
            self._dec = ctr(key_s, iv_s).decryptor()
            self._mac_in, self._mac_out = mac_s, mac_c

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass
