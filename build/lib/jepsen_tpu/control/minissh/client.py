"""Blocking SSH client over minissh.transport.

One connection, publickey (or password) userauth, one exec channel —
exactly the shape SshCliRemote's per-command `ssh`/`scp` subprocesses
need (control/remotes.py:163-175 runs one command per invocation).
"""

from __future__ import annotations

import socket

from cryptography.hazmat.primitives import serialization

from . import scp as scp_proto
from .transport import (
    MSG_CHANNEL_CLOSE,
    MSG_CHANNEL_DATA,
    MSG_CHANNEL_EOF,
    MSG_CHANNEL_EXTENDED_DATA,
    MSG_CHANNEL_OPEN,
    MSG_CHANNEL_OPEN_CONFIRMATION,
    MSG_CHANNEL_OPEN_FAILURE,
    MSG_CHANNEL_REQUEST,
    MSG_CHANNEL_SUCCESS,
    MSG_CHANNEL_FAILURE,
    MSG_CHANNEL_WINDOW_ADJUST,
    MSG_SERVICE_ACCEPT,
    MSG_SERVICE_REQUEST,
    MSG_USERAUTH_FAILURE,
    MSG_USERAUTH_REQUEST,
    MSG_USERAUTH_SUCCESS,
    Buf,
    SshError,
    Transport,
    hostkey_blob,
    sig_blob,
    sstr,
    u32,
)

WINDOW = 1 << 30
MAX_PACKET = 32768


class SshClient:
    def __init__(self, host: str, port: int = 22, *, user: str = "root",
                 key_path: str | None = None,
                 password: str | None = None,
                 timeout: float = 30.0):
        self.host = host
        self.port = port
        self.user = user
        self.key_path = key_path
        self.password = password
        self.timeout = timeout
        self.tr: Transport | None = None
        self._chan_peer: int | None = None

    # -- lifecycle ---------------------------------------------------------

    def connect(self) -> "SshClient":
        sock = socket.create_connection(
            (self.host, self.port), timeout=self.timeout
        )
        sock.settimeout(None)
        self.tr = Transport(sock, server_side=False)
        self.tr.handshake()
        self._userauth()
        return self

    def close(self) -> None:
        if self.tr:
            self.tr.close()

    def __enter__(self):
        return self.connect()

    def __exit__(self, *exc):
        self.close()

    # -- auth --------------------------------------------------------------

    def _userauth(self) -> None:
        tr = self.tr
        tr.write_packet(
            bytes([MSG_SERVICE_REQUEST]) + sstr(b"ssh-userauth")
        )
        pkt = tr.read_message()
        if pkt[0] != MSG_SERVICE_ACCEPT:
            raise SshError("userauth service refused")

        if self.key_path:
            with open(self.key_path, "rb") as f:
                key = serialization.load_ssh_private_key(f.read(), None)
            blob = hostkey_blob(key.public_key())
            base = (
                bytes([MSG_USERAUTH_REQUEST])
                + sstr(self.user.encode())
                + sstr(b"ssh-connection")
                + sstr(b"publickey")
                + b"\x01"
                + sstr(b"ssh-ed25519")
                + sstr(blob)
            )
            sig = key.sign(sstr(tr.session_id) + base)
            tr.write_packet(base + sstr(sig_blob(sig)))
        elif self.password is not None:
            tr.write_packet(
                bytes([MSG_USERAUTH_REQUEST])
                + sstr(self.user.encode())
                + sstr(b"ssh-connection")
                + sstr(b"password")
                + b"\x00"
                + sstr(self.password.encode())
            )
        else:
            raise SshError("no key_path or password configured")
        pkt = tr.read_message()
        if pkt[0] == MSG_USERAUTH_SUCCESS:
            return
        if pkt[0] == MSG_USERAUTH_FAILURE:
            raise SshError("authentication failed")
        raise SshError(f"unexpected userauth reply {pkt[0]}")

    # -- exec --------------------------------------------------------------

    def _open_session(self) -> None:
        tr = self.tr
        tr.write_packet(
            bytes([MSG_CHANNEL_OPEN]) + sstr(b"session")
            + u32(0) + u32(WINDOW) + u32(MAX_PACKET)
        )
        while True:
            pkt = tr.read_message()
            if pkt[0] == MSG_CHANNEL_OPEN_CONFIRMATION:
                buf = Buf(pkt)
                buf.byte()
                buf.u32()  # our id (0)
                self._chan_peer = buf.u32()
                return
            if pkt[0] == MSG_CHANNEL_OPEN_FAILURE:
                raise SshError("channel open refused")

    def run(self, command: str, stdin: bytes = b"",
            stdout_cb=None, stderr_cb=None) -> tuple[int, bytes, bytes]:
        """Execs `command`; returns (exit_status, stdout, stderr).
        Callbacks, when given, stream chunks as they arrive (the CLI
        shim uses them to behave like a real ssh)."""
        tr = self.tr
        self._open_session()
        peer = self._chan_peer
        tr.write_packet(
            bytes([MSG_CHANNEL_REQUEST]) + u32(peer) + sstr(b"exec")
            + b"\x01" + sstr(command.encode())
        )
        # exec reply may interleave with early data; collect as we go
        out, err = [], []
        status = 255
        sender = None
        got_close = False
        got_reply = False

        def send_stdin():
            # A dedicated sender keeps the main loop reading: a large
            # stdin against an echoing command would otherwise deadlock
            # (we block in sendall while the server blocks sending
            # output nobody is reading).  write_packet is lock-
            # protected, so the only other write — the final CLOSE —
            # is safe; it happens after join().
            try:
                for i in range(0, len(stdin), MAX_PACKET - 64):
                    chunk = stdin[i:i + MAX_PACKET - 64]
                    tr.write_packet(
                        bytes([MSG_CHANNEL_DATA]) + u32(peer) + sstr(chunk)
                    )
                tr.write_packet(bytes([MSG_CHANNEL_EOF]) + u32(peer))
            except OSError:
                pass  # connection died; main loop reports it

        while not got_close:
            if got_reply and sender is None:
                import threading

                sender = threading.Thread(target=send_stdin, daemon=True)
                sender.start()
            pkt = tr.read_message()
            buf = Buf(pkt)
            t = buf.byte()
            if t == MSG_CHANNEL_SUCCESS:
                got_reply = True
            elif t == MSG_CHANNEL_FAILURE:
                raise SshError("exec request refused")
            elif t == MSG_CHANNEL_DATA:
                buf.u32()
                data = buf.string()
                out.append(data)
                if stdout_cb:
                    stdout_cb(data)
            elif t == MSG_CHANNEL_EXTENDED_DATA:
                buf.u32()
                buf.u32()  # type 1 = stderr
                data = buf.string()
                err.append(data)
                if stderr_cb:
                    stderr_cb(data)
            elif t == MSG_CHANNEL_REQUEST:
                buf.u32()
                if buf.string() == b"exit-status":
                    buf.bool()
                    status = buf.u32()
            elif t == MSG_CHANNEL_CLOSE:
                got_close = True
            elif t in (MSG_CHANNEL_EOF, MSG_CHANNEL_WINDOW_ADJUST):
                continue
            else:
                raise SshError(f"unexpected message {t} during exec")
        if sender is not None:
            sender.join(timeout=30)
        try:
            tr.write_packet(bytes([MSG_CHANNEL_CLOSE]) + u32(peer))
        except OSError:
            pass  # peer may already have torn the connection down
        return status, b"".join(out), b"".join(err)

    # -- scp ---------------------------------------------------------------

    def scp_upload(self, local: str, remote: str, *,
                   recursive: bool = False, preserve: bool = False) -> int:
        flags = "-t" + ("r" if recursive else "") + \
            ("p" if preserve else "")
        return self._scp(f"scp {flags} {_q(remote)}", "source", local,
                         recursive, preserve)

    def scp_download(self, remote: str, local: str, *,
                     recursive: bool = False, preserve: bool = False) -> int:
        flags = "-f" + ("r" if recursive else "") + \
            ("p" if preserve else "")
        return self._scp(f"scp {flags} {_q(remote)}", "sink", local,
                         recursive, preserve)

    def _scp(self, command: str, role: str, local_path: str,
             recursive: bool, preserve: bool) -> int:
        tr = self.tr
        self._open_session()
        peer = self._chan_peer
        tr.write_packet(
            bytes([MSG_CHANNEL_REQUEST]) + u32(peer) + sstr(b"exec")
            + b"\x01" + sstr(command.encode())
        )
        pkt = tr.read_message()
        if pkt[0] == MSG_CHANNEL_FAILURE:
            raise SshError("scp exec refused")
        io = _ClientChannelIO(self, peer,
                              preread=pkt if pkt[0] != MSG_CHANNEL_SUCCESS
                              else None)
        try:
            if role == "source":
                scp_proto.speak_source(io, local_path,
                                       recursive=recursive,
                                       preserve=preserve)
                try:
                    tr.write_packet(bytes([MSG_CHANNEL_EOF]) + u32(peer))
                except OSError:
                    pass
            else:
                scp_proto.speak_sink(io, local_path,
                                     recursive=recursive,
                                     preserve=preserve)
        except scp_proto.ScpError as e:
            raise SshError(f"scp failed: {e}") from e
        # drain to exit-status
        status = 0
        while True:
            pkt = io.pending_control or self.tr.read_message()
            io.pending_control = None
            buf = Buf(pkt)
            t = buf.byte()
            if t == MSG_CHANNEL_REQUEST:
                buf.u32()
                if buf.string() == b"exit-status":
                    buf.bool()
                    status = buf.u32()
            elif t == MSG_CHANNEL_CLOSE:
                break
            elif t in (MSG_CHANNEL_DATA, MSG_CHANNEL_EXTENDED_DATA,
                       MSG_CHANNEL_EOF, MSG_CHANNEL_WINDOW_ADJUST):
                continue
            else:
                raise SshError(f"unexpected message {t} after scp")
        try:
            tr.write_packet(bytes([MSG_CHANNEL_CLOSE]) + u32(peer))
        except OSError:
            pass
        return status


def _q(path: str) -> str:
    import shlex

    return shlex.quote(path)


class _ClientChannelIO(scp_proto.ScpIO):
    """scp stream over the client's channel; control messages seen
    mid-stream (exit-status, close) are parked for the drain loop."""

    def __init__(self, client: SshClient, peer: int, preread=None):
        self.client = client
        self.peer = peer
        self.buf = b""
        self.eof = False
        self.pending_control = None
        self._preread = preread

    def read(self, n: int) -> bytes:
        while not self.buf and not self.eof:
            if self._preread is not None:
                pkt, self._preread = self._preread, None
            else:
                pkt = self.client.tr.read_message()
            buf = Buf(pkt)
            t = buf.byte()
            if t == MSG_CHANNEL_DATA:
                buf.u32()
                self.buf += buf.string()
            elif t == MSG_CHANNEL_EOF:
                self.eof = True
            elif t in (MSG_CHANNEL_CLOSE, MSG_CHANNEL_REQUEST):
                self.pending_control = pkt
                self.eof = True
            elif t in (MSG_CHANNEL_WINDOW_ADJUST, MSG_CHANNEL_SUCCESS,
                       MSG_CHANNEL_EXTENDED_DATA):
                continue
            else:
                raise SshError(f"unexpected message {t} in scp stream")
        out, self.buf = self.buf[:n], self.buf[n:]
        return out

    def write(self, b: bytes) -> None:
        for i in range(0, len(b), MAX_PACKET - 64):
            chunk = b[i:i + MAX_PACKET - 64]
            self.client.tr.write_packet(
                bytes([MSG_CHANNEL_DATA]) + u32(self.peer) + sstr(chunk)
            )
