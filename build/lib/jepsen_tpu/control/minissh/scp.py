"""The classic scp wire protocol, speaker-agnostic.

scp runs over an exec channel: one side is started with `scp -t <dst>`
(sink: receives files) or `scp -f <src>` (source: sends files); the
other side speaks the matching half.  Records:

    C<mode> <size> <name>\n   file, then <size> raw bytes + \0
    D<mode> 0 <name>\n        descend into directory
    E\n                       pop directory
    T<mtime> 0 <atime> 0\n    times for the next C/D (with -p)

Every record and file body is acknowledged with \0 (\1 = warning,
\2 = fatal, each followed by a message line).

Both the in-process server (server.py) and the scp client shim
(tools/sshbin/scp) call into these two functions with a tiny IO
adapter, so there is exactly one implementation of the protocol.
Reference consumption: control/scp.clj:29-57 shells out to scp the
same way SshCliRemote does.
"""

from __future__ import annotations

import os
import stat as stat_mod


class ScpIO:
    """Adapter the speakers use: a read/write byte stream."""

    def read(self, n: int) -> bytes:  # pragma: no cover - interface
        raise NotImplementedError

    def write(self, b: bytes) -> None:  # pragma: no cover - interface
        raise NotImplementedError


class ScpError(Exception):
    pass


def _read_exact(io: ScpIO, n: int) -> bytes:
    out = b""
    while len(out) < n:
        chunk = io.read(n - len(out))
        if not chunk:
            raise ScpError("unexpected EOF in scp stream")
        out += chunk
    return out


def _read_line(io: ScpIO) -> bytes:
    out = b""
    while True:
        c = io.read(1)
        if not c:
            raise ScpError("unexpected EOF in scp record")
        if c == b"\n":
            return out
        out += c


def _ack(io: ScpIO) -> None:
    io.write(b"\x00")


def _expect_ack(io: ScpIO) -> None:
    c = _read_exact(io, 1)
    if c == b"\x00":
        return
    msg = _read_line(io).decode(errors="replace")
    raise ScpError(f"scp peer error ({c[0]}): {msg}")


def speak_source(io: ScpIO, path: str, *, recursive: bool = False,
                 preserve: bool = False) -> None:
    """Sends `path` (file, or directory with recursive=True) to a sink
    on the other end."""
    _expect_ack(io)  # sink announces readiness

    def send_times(st) -> None:
        io.write(
            f"T{int(st.st_mtime)} 0 {int(st.st_atime)} 0\n".encode()
        )
        _expect_ack(io)

    def send_file(p: str) -> None:
        st = os.stat(p)
        if preserve:
            send_times(st)
        mode = stat_mod.S_IMODE(st.st_mode)
        name = os.path.basename(p.rstrip("/")) or "/"
        io.write(f"C{mode:04o} {st.st_size} {name}\n".encode())
        _expect_ack(io)
        with open(p, "rb") as f:
            left = st.st_size
            while left:
                chunk = f.read(min(65536, left))
                if not chunk:
                    raise ScpError(f"{p} shrank while sending")
                io.write(chunk)
                left -= len(chunk)
        io.write(b"\x00")
        _expect_ack(io)

    def send_dir(p: str) -> None:
        st = os.stat(p)
        if preserve:
            send_times(st)
        mode = stat_mod.S_IMODE(st.st_mode)
        name = os.path.basename(p.rstrip("/")) or "/"
        io.write(f"D{mode:04o} 0 {name}\n".encode())
        _expect_ack(io)
        for entry in sorted(os.listdir(p)):
            walk(os.path.join(p, entry))
        io.write(b"E\n")
        _expect_ack(io)

    def walk(p: str) -> None:
        if os.path.isdir(p):
            if not recursive:
                raise ScpError(f"{p} is a directory (no -r)")
            send_dir(p)
        else:
            send_file(p)

    walk(path)


def speak_sink(io: ScpIO, dst: str, *, recursive: bool = False,
               preserve: bool = False) -> None:
    """Receives files into `dst` from a source on the other end.  When
    dst is an existing directory, entries land inside it; otherwise a
    single incoming file is written at dst itself."""
    _ack(io)  # announce readiness
    dst_is_dir = os.path.isdir(dst)
    stack = [dst]
    pending_times = None

    def target_for(name: str) -> str:
        base = stack[-1]
        if len(stack) > 1 or dst_is_dir:
            return os.path.join(base, name)
        return base

    while True:
        try:
            line = _read_line(io)
        except ScpError:
            return  # clean EOF between records: source is done
        if not line:
            continue
        kind, rest = line[:1], line[1:].decode(errors="replace")
        if kind == b"T":
            parts = rest.split()
            pending_times = (int(parts[2]), int(parts[0]))
            _ack(io)
        elif kind == b"C":
            mode_s, size_s, name = rest.split(" ", 2)
            size = int(size_s)
            path = target_for(os.path.basename(name))
            _ack(io)
            with open(path, "wb") as f:
                left = size
                while left:
                    chunk = io.read(min(65536, left))
                    if not chunk:
                        raise ScpError("EOF mid-file in scp sink")
                    f.write(chunk)
                    left -= len(chunk)
            _expect_ack(io)  # source's end-of-body \0
            os.chmod(path, int(mode_s, 8))
            if preserve and pending_times:
                os.utime(path, pending_times)
            pending_times = None
            _ack(io)
            if len(stack) == 1 and not dst_is_dir and not recursive:
                return  # single-file transfer complete
        elif kind == b"D":
            mode_s, _zero, name = rest.split(" ", 2)
            path = target_for(os.path.basename(name))
            os.makedirs(path, exist_ok=True)
            os.chmod(path, int(mode_s, 8))
            if preserve and pending_times:
                os.utime(path, pending_times)
            pending_times = None
            stack.append(path)
            _ack(io)
        elif kind == b"E":
            if len(stack) > 1:
                stack.pop()
            _ack(io)
            if len(stack) == 1 and not dst_is_dir:
                return
        elif kind in (b"\x01", b"\x02"):
            raise ScpError(f"scp source error: {rest}")
        else:
            io.write(b"\x01bad record\n")
            raise ScpError(f"unknown scp record {line!r}")
