"""minissh: a self-contained SSH-2 implementation (client + server).

Why this exists: the reference exercises its control layer against live
sshd nodes (control_test.clj:157-161 round-trips both remotes; the
docker harness provides the nodes).  This environment ships NO ssh
client, NO sshd, and no paramiko — so the round-2 integration suite
could never execute (VERDICT r2 "missing" #3).  Rather than mock the
transport, this package implements the actual SSH-2 wire protocol over
the `cryptography` primitives that ARE in the image:

* transport.py — RFC 4253 binary packet protocol + RFC 8731
  curve25519-sha256 key exchange, ssh-ed25519 host keys, aes128-ctr +
  hmac-sha2-256; one ciphersuite, no rekeying (sessions are short).
* server.py — threaded exec server: channels, publickey/password
  userauth, subprocess exec with streamed stdio + exit status, and a
  built-in scp sink/source (the image has no scp binary either).
* client.py — blocking client: connect, auth, run one exec channel.
* scp.py — the classic scp wire protocol, shared by both sides.
* tools/sshbin/{ssh,scp} — argv-compatible shims so SshCliRemote
  (control/remotes.py) executes its REAL command lines end-to-end.

Single-purpose by design: one ciphersuite, one channel per connection
(SshCliRemote opens a fresh connection per command), 1 GiB windows in
lieu of flow control.  Interoperability with OpenSSH is a non-goal —
wire-level self-consistency plus RFC-faithful framing is.
"""

from .client import SshClient
from .server import MiniSshServer, generate_keypair

__all__ = ["SshClient", "MiniSshServer", "generate_keypair"]
