"""Threaded SSH exec server over minissh.transport.

Serves the slice of SSH that jepsen-tpu's control layer uses
(control/remotes.py SshCliRemote; reference behavior at
control_test.clj:157-161): publickey/password userauth, one "session"
channel per connection, "exec" with streamed stdin/stdout/stderr and
exit-status, plus a built-in scp sink/source (the image has no scp
binary, so `scp -t/-f` exec commands are served in-process through
scp.py).

Commands run as the server's own user via bash -c in `root_dir`.  This
is a test fixture standing in for a cluster node, not a hardened
daemon: it binds loopback by default and trusts its configured keys.
"""

from __future__ import annotations

import os
import shlex
import socket
import subprocess
import threading

from cryptography.hazmat.primitives.asymmetric.ed25519 import (
    Ed25519PrivateKey,
)
from cryptography.hazmat.primitives import serialization

from . import scp as scp_proto
from .transport import (
    MSG_CHANNEL_CLOSE,
    MSG_CHANNEL_DATA,
    MSG_CHANNEL_EOF,
    MSG_CHANNEL_EXTENDED_DATA,
    MSG_CHANNEL_OPEN,
    MSG_CHANNEL_OPEN_CONFIRMATION,
    MSG_CHANNEL_OPEN_FAILURE,
    MSG_CHANNEL_REQUEST,
    MSG_CHANNEL_SUCCESS,
    MSG_CHANNEL_FAILURE,
    MSG_CHANNEL_WINDOW_ADJUST,
    MSG_SERVICE_ACCEPT,
    MSG_SERVICE_REQUEST,
    MSG_USERAUTH_FAILURE,
    MSG_USERAUTH_PK_OK,
    MSG_USERAUTH_REQUEST,
    MSG_USERAUTH_SUCCESS,
    Buf,
    SshError,
    Transport,
    hostkey_blob,
    pub_from_blob,
    sig_from_blob,
    sstr,
    u32,
)

WINDOW = 1 << 30
MAX_PACKET = 32768


def generate_keypair(directory: str, name: str = "id_ed25519"):
    """Writes an OpenSSH-format ed25519 keypair into `directory`;
    returns (private_path, public_blob).  Replaces ssh-keygen, which
    the image doesn't ship."""
    key = Ed25519PrivateKey.generate()
    priv_path = os.path.join(directory, name)
    with open(priv_path, "wb") as f:
        f.write(key.private_bytes(
            serialization.Encoding.PEM,
            serialization.PrivateFormat.OpenSSH,
            serialization.NoEncryption(),
        ))
    os.chmod(priv_path, 0o600)
    blob = hostkey_blob(key.public_key())
    import base64

    with open(priv_path + ".pub", "wb") as f:
        f.write(b"ssh-ed25519 " + base64.b64encode(blob) + b" minissh\n")
    return priv_path, blob


class MiniSshServer:
    """One loopback "node".  start() binds an ephemeral port; .port
    tells clients where to dial."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0, *,
                 authorized_keys: list[bytes] | None = None,
                 passwords: dict[str, str] | None = None,
                 root_dir: str | None = None,
                 hostname: str | None = None):
        self.host = host
        self.port = port
        self.authorized_keys = list(authorized_keys or [])
        self.passwords = dict(passwords or {})
        self.root_dir = root_dir
        self.hostname = hostname
        self.host_key = Ed25519PrivateKey.generate()
        self._sock: socket.socket | None = None
        self._threads: list[threading.Thread] = []
        self._stopping = threading.Event()

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "MiniSshServer":
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind((self.host, self.port))
        s.listen(32)
        self.port = s.getsockname()[1]
        self._sock = s
        t = threading.Thread(target=self._accept_loop, daemon=True)
        t.start()
        self._threads.append(t)
        return self

    def stop(self) -> None:
        self._stopping.set()
        if self._sock:
            try:
                self._sock.close()
            except OSError:
                pass

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    # -- connection handling -----------------------------------------------

    def _accept_loop(self) -> None:
        while not self._stopping.is_set():
            try:
                conn, _addr = self._sock.accept()
            except OSError:
                return
            t = threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True
            )
            t.start()
            self._threads.append(t)

    def _serve_conn(self, conn: socket.socket) -> None:
        tr = Transport(conn, server_side=True, host_key=self.host_key)
        try:
            tr.handshake()
            if not self._userauth(tr):
                return
            self._session(tr)
            # Give the client a beat to send its own CLOSE before the
            # socket drops, so its final writes don't see EPIPE.
            deadline = 5.0
            while deadline > 0 and tr.readable(timeout=0.25):
                deadline -= 0.25
                pkt = tr.read_message()
                if pkt and pkt[0] == MSG_CHANNEL_CLOSE:
                    break
        except (SshError, OSError):
            pass
        finally:
            tr.close()

    def _userauth(self, tr: Transport) -> bool:
        while True:
            pkt = tr.read_message()
            buf = Buf(pkt)
            t = buf.byte()
            if t == MSG_SERVICE_REQUEST:
                svc = buf.string()
                tr.write_packet(
                    bytes([MSG_SERVICE_ACCEPT]) + sstr(svc)
                )
                continue
            if t != MSG_USERAUTH_REQUEST:
                raise SshError(f"expected USERAUTH_REQUEST, got {t}")
            user = buf.string().decode()
            buf.string()  # service: ssh-connection
            method = buf.string()
            if method == b"publickey":
                has_sig = buf.bool()
                alg = buf.string()
                blob = buf.string()
                if alg != b"ssh-ed25519" or blob not in self.authorized_keys:
                    self._auth_fail(tr)
                    continue
                if not has_sig:
                    tr.write_packet(
                        bytes([MSG_USERAUTH_PK_OK]) + sstr(alg) + sstr(blob)
                    )
                    continue
                sig = sig_from_blob(buf.string())
                # signed blob (RFC 4252 §7): session_id + the request
                # up to and including the key blob, sans signature
                signed = (
                    sstr(tr.session_id)
                    + bytes([MSG_USERAUTH_REQUEST])
                    + sstr(user.encode())
                    + sstr(b"ssh-connection")
                    + sstr(b"publickey")
                    + b"\x01"
                    + sstr(alg)
                    + sstr(blob)
                )
                try:
                    pub_from_blob(blob).verify(sig, signed)
                except Exception:
                    self._auth_fail(tr)
                    continue
                tr.write_packet(bytes([MSG_USERAUTH_SUCCESS]))
                return True
            if method == b"password":
                buf.bool()
                pw = buf.string().decode()
                if self.passwords.get(user) == pw:
                    tr.write_packet(bytes([MSG_USERAUTH_SUCCESS]))
                    return True
                self._auth_fail(tr)
                continue
            self._auth_fail(tr)

    def _auth_fail(self, tr: Transport) -> None:
        tr.write_packet(
            bytes([MSG_USERAUTH_FAILURE])
            + sstr(b"publickey,password") + b"\x00"
        )

    # -- session channel ---------------------------------------------------

    def _session(self, tr: Transport) -> None:
        chan_peer = None
        while True:
            pkt = tr.read_message()
            buf = Buf(pkt)
            t = buf.byte()
            if t == MSG_CHANNEL_OPEN:
                kind = buf.string()
                peer_id = buf.u32()
                if kind != b"session":
                    tr.write_packet(
                        bytes([MSG_CHANNEL_OPEN_FAILURE]) + u32(peer_id)
                        + u32(3) + sstr(b"only session") + sstr(b"")
                    )
                    continue
                chan_peer = peer_id
                tr.write_packet(
                    bytes([MSG_CHANNEL_OPEN_CONFIRMATION])
                    + u32(peer_id) + u32(0) + u32(WINDOW) + u32(MAX_PACKET)
                )
            elif t == MSG_CHANNEL_REQUEST:
                buf.u32()  # our channel id (0)
                req = buf.string()
                want_reply = buf.bool()
                if req == b"exec" and chan_peer is not None:
                    command = buf.string().decode()
                    if want_reply:
                        tr.write_packet(
                            bytes([MSG_CHANNEL_SUCCESS]) + u32(chan_peer)
                        )
                    self._exec(tr, chan_peer, command)
                    return
                if req == b"env":
                    if want_reply:
                        tr.write_packet(
                            bytes([MSG_CHANNEL_SUCCESS]) + u32(chan_peer)
                        )
                elif want_reply:
                    tr.write_packet(
                        bytes([MSG_CHANNEL_FAILURE]) + u32(chan_peer)
                    )
            elif t in (MSG_CHANNEL_WINDOW_ADJUST, MSG_CHANNEL_EOF):
                continue
            elif t == MSG_CHANNEL_CLOSE:
                return
            else:
                raise SshError(f"unexpected message {t} pre-exec")

    # -- exec --------------------------------------------------------------

    def _exec(self, tr: Transport, peer: int, command: str) -> None:
        scp_argv = self._parse_scp(command)
        if scp_argv is not None:
            self._exec_scp(tr, peer, *scp_argv)
            return

        env = dict(os.environ)
        if self.hostname:
            # lets `hostname` report the node name without uts
            # namespaces: tests and DB setup key on it
            env["MINISSH_HOSTNAME"] = self.hostname
            command = (
                f"hostname() {{ echo {shlex.quote(self.hostname)}; }}; "
                f"export -f hostname >/dev/null 2>&1; " + command
            )
        proc = subprocess.Popen(
            ["/bin/bash", "-c", command],
            stdin=subprocess.PIPE,
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            cwd=self.root_dir,
            env=env,
        )

        def pump(stream, mtype, extended):
            while True:
                chunk = stream.read(32768)
                if not chunk:
                    return
                if extended:
                    tr.write_packet(
                        bytes([mtype]) + u32(peer) + u32(1) + sstr(chunk)
                    )
                else:
                    tr.write_packet(
                        bytes([mtype]) + u32(peer) + sstr(chunk)
                    )

        t_out = threading.Thread(
            target=pump, args=(proc.stdout, MSG_CHANNEL_DATA, False),
            daemon=True,
        )
        t_err = threading.Thread(
            target=pump, args=(proc.stderr, MSG_CHANNEL_EXTENDED_DATA, True),
            daemon=True,
        )
        t_out.start()
        t_err.start()

        # Main loop: feed stdin from channel data until client EOF.
        stdin_open = True
        closed = False
        while True:
            if not tr.readable(timeout=0.05):
                if proc.poll() is not None:
                    break
                continue
            try:
                pkt = tr.read_message()
            except (SshError, OSError):
                proc.kill()
                closed = True
                break
            buf = Buf(pkt)
            t = buf.byte()
            if t == MSG_CHANNEL_DATA:
                buf.u32()
                data = buf.string()
                if stdin_open:
                    try:
                        proc.stdin.write(data)
                        proc.stdin.flush()
                    except (BrokenPipeError, ValueError):
                        stdin_open = False
            elif t == MSG_CHANNEL_EOF:
                if stdin_open:
                    try:
                        proc.stdin.close()
                    except OSError:
                        pass
                    stdin_open = False
            elif t == MSG_CHANNEL_CLOSE:
                proc.kill()
                closed = True
                break
            elif t == MSG_CHANNEL_WINDOW_ADJUST:
                continue
        if stdin_open:
            try:
                proc.stdin.close()
            except OSError:
                pass
        rc = proc.wait()
        t_out.join(timeout=30)
        t_err.join(timeout=30)
        if not closed:
            tr.write_packet(
                bytes([MSG_CHANNEL_REQUEST]) + u32(peer)
                + sstr(b"exit-status") + b"\x00" + u32(rc & 0xFF)
            )
            tr.write_packet(bytes([MSG_CHANNEL_EOF]) + u32(peer))
            tr.write_packet(bytes([MSG_CHANNEL_CLOSE]) + u32(peer))

    # -- scp ---------------------------------------------------------------

    @staticmethod
    def _parse_scp(command: str):
        """(mode, path, recursive, preserve) when the exec command is a
        classic scp server invocation, else None."""
        try:
            argv = shlex.split(command)
        except ValueError:
            return None
        if not argv or argv[0] != "scp":
            return None
        mode = None
        recursive = preserve = False
        path = None
        for a in argv[1:]:
            if a.startswith("-") and len(a) > 1 and a != "--":
                for c in a[1:]:
                    if c == "t":
                        mode = "sink"
                    elif c == "f":
                        mode = "source"
                    elif c == "r":
                        recursive = True
                    elif c == "p":
                        preserve = True
                    # -d, -v, -C: accepted, no-op here
            else:
                path = a
        if mode is None or path is None:
            return None
        return mode, path, recursive, preserve

    def _exec_scp(self, tr: Transport, peer: int, mode: str, path: str,
                  recursive: bool, preserve: bool) -> None:
        io = _ChannelIO(tr, peer)
        rc = 0
        try:
            if self.root_dir and not os.path.isabs(path):
                path = os.path.join(self.root_dir, path)
            if mode == "sink":
                scp_proto.speak_sink(io, path, recursive=recursive,
                                     preserve=preserve)
            else:
                scp_proto.speak_source(io, path, recursive=recursive,
                                       preserve=preserve)
        except (scp_proto.ScpError, OSError) as e:
            try:
                io.write(b"\x02" + str(e).encode() + b"\n")
            except (SshError, OSError):
                pass
            rc = 1
        tr.write_packet(
            bytes([MSG_CHANNEL_REQUEST]) + u32(peer)
            + sstr(b"exit-status") + b"\x00" + u32(rc)
        )
        tr.write_packet(bytes([MSG_CHANNEL_EOF]) + u32(peer))
        tr.write_packet(bytes([MSG_CHANNEL_CLOSE]) + u32(peer))


class _ChannelIO(scp_proto.ScpIO):
    """scp byte stream over one channel's DATA messages."""

    def __init__(self, tr: Transport, peer: int):
        self.tr = tr
        self.peer = peer
        self.buf = b""
        self.eof = False

    def read(self, n: int) -> bytes:
        while not self.buf and not self.eof:
            pkt = self.tr.read_message()
            buf = Buf(pkt)
            t = buf.byte()
            if t == MSG_CHANNEL_DATA:
                buf.u32()
                self.buf += buf.string()
            elif t in (MSG_CHANNEL_EOF, MSG_CHANNEL_CLOSE):
                self.eof = True
            elif t == MSG_CHANNEL_WINDOW_ADJUST:
                continue
            else:
                raise SshError(f"unexpected message {t} in scp stream")
        out, self.buf = self.buf[:n], self.buf[n:]
        return out

    def write(self, b: bytes) -> None:
        for i in range(0, len(b), MAX_PACKET - 64):
            chunk = b[i:i + MAX_PACKET - 64]
            self.tr.write_packet(
                bytes([MSG_CHANNEL_DATA]) + u32(self.peer) + sstr(chunk)
            )


def main(argv=None) -> int:
    """Standalone node daemon: `python -m jepsen_tpu.control.minissh.
    server --host 10.x.y.z --authorized-keys id_ed25519.pub`.  Run
    inside a network namespace (ip netns exec), this turns a namespace
    into a full SSH-reachable cluster node — the netns analogue of the
    docker harness's sshd containers (tools/cluster)."""
    import argparse
    import base64
    import time

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=2200)
    ap.add_argument("--authorized-keys", required=True,
                    help="OpenSSH .pub file; each ssh-ed25519 line is "
                    "accepted for any user")
    ap.add_argument("--hostname", default=None)
    ap.add_argument("--root-dir", default=None)
    args = ap.parse_args(argv)

    blobs = []
    with open(args.authorized_keys, "rb") as f:
        for line in f:
            parts = line.split()
            if len(parts) >= 2 and parts[0] == b"ssh-ed25519":
                blobs.append(base64.b64decode(parts[1]))
    if not blobs:
        ap.error(f"no ssh-ed25519 keys in {args.authorized_keys}")

    srv = MiniSshServer(
        args.host, args.port, authorized_keys=blobs,
        hostname=args.hostname, root_dir=args.root_dir,
    ).start()
    print(f"listening {args.host}:{srv.port}", flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        srv.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
