"""lazyfs: lose data that was written but never fsynced.

Equivalent of /root/reference/jepsen/src/jepsen/lazyfs.clj (:22-100):
mount a directory on the lazyfs FUSE filesystem, whose page cache can
be dropped on command — un-fsynced writes vanish, exactly the fault
class real disks exhibit on power loss.  The pieces:

  * `LazyFS` — the file layout map for one mounted directory
    (lazyfs.clj:110-150): backing data dir, control fifo, config, log.
  * `install(sess)` — clone + build lazyfs on the node
    (lazyfs.clj:68-108; needs network + fuse on the DB node, so
    container/integration environments only).
  * `mount(sess)` / `umount(sess)` — lifecycle (lazyfs.clj:165-220).
  * `lose_unfsynced_writes(sess)` — the fault itself, sent over the
    fifo (lazyfs.clj:222-232 fifo! + "lazyfs::clear-cache").
  * `LazyFSDB` — wraps any DB so its directory rides lazyfs and its
    logs include the lazyfs log (lazyfs.clj DB record).
  * `lazyfs_package` — a nemesis package injecting the fault on a
    cycle, routed to the wrapped DB (reusable fault layer, unlike a
    per-DB opt-in).
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Any, Optional

from . import db as jdb
from .control import Session, on_nodes
from .history import Op
from .nemesis.core import Nemesis

log = logging.getLogger(__name__)

REPO_URL = "https://github.com/dsrhaslab/lazyfs.git"
COMMIT = "0.2.0"
INSTALL_DIR = "/opt/jepsen-tpu/lazyfs"
BIN = f"{INSTALL_DIR}/lazyfs/build/lazyfs"
FUSE_DEV = "/dev/fuse"


@dataclass
class LazyFS:
    """File layout for one lazyfs mount (lazyfs.clj:110-150)."""

    dir: str
    lazyfs_dir: str = ""
    data_dir: str = ""
    fifo: str = ""
    config_file: str = ""
    log_file: str = ""
    user: str = "root"
    cache_size: str = "0.5GB"

    def __post_init__(self) -> None:
        self.lazyfs_dir = self.lazyfs_dir or self.dir + ".lazyfs"
        self.data_dir = self.data_dir or self.lazyfs_dir + "/data"
        self.fifo = self.fifo or self.lazyfs_dir + "/fifo"
        self.config_file = self.config_file or self.lazyfs_dir + "/config"
        self.log_file = self.log_file or self.lazyfs_dir + "/log"

    def config(self) -> str:
        """Config file text (lazyfs.clj:42-60)."""
        return (
            "[faults]\n"
            f'fifo_path="{self.fifo}"\n'
            "[cache]\n"
            "apply_eviction=false\n"
            "[cache.simple]\n"
            f'custom_size="{self.cache_size}"\n'
            "blocks_per_page=1\n"
            "[filesystem]\n"
            f'logfile="{self.log_file}"\n'
            "log_all_operations=false\n"
        )

    # -- lifecycle --------------------------------------------------------

    def install(self, sess: Session) -> None:
        """Builds lazyfs on the node (lazyfs.clj:68-108).  Node
        environment prep (fuse device, fuse.conf) always runs — a fresh
        container may carry a prebuilt /opt volume; only the fetch +
        builds are skipped when the pinned commit's binary is already
        there (every DB cycle calls this, and `git clean -fx` would
        otherwise force a from-scratch rebuild per run)."""
        with sess.su():
            # Environment prep: idempotent, must run even when the
            # binary is cached (LXC/containers lose /dev/fuse).
            if sess.exec_star("test", "-e", FUSE_DEV).get("exit") != 0:
                sess.exec("mknod", FUSE_DEV, "c", "10", "229")
                sess.exec("chmod", "a+rw", FUSE_DEV)
            built = sess.exec_star("test", "-x", BIN).get("exit") == 0
            if built:
                at = sess.exec_star(
                    "git", "-C", INSTALL_DIR, "describe", "--tags",
                    "--always",
                )
                if COMMIT in (at.get("out") or ""):
                    # Cached build: fuse.conf exists iff fuse3 was ever
                    # installed; gate the sed so a stripped image
                    # doesn't crash here.
                    if sess.exec_star(
                        "test", "-e", "/etc/fuse.conf"
                    ).get("exit") == 0:
                        sess.exec(
                            "sed", "-i",
                            r"/\s*user_allow_other/s/^#//g",
                            "/etc/fuse.conf",
                        )
                    return
            sess.exec(
                "env", "DEBIAN_FRONTEND=noninteractive",
                "apt-get", "install", "-y",
                "g++", "cmake", "libfuse3-dev", "libfuse3-3", "fuse3",
                "git",
            )
            # fuse3 ships /etc/fuse.conf; enable user_allow_other.
            sess.exec(
                "sed", "-i", r"/\s*user_allow_other/s/^#//g",
                "/etc/fuse.conf",
            )
            if sess.exec_star("test", "-e", INSTALL_DIR).get("exit") != 0:
                sess.exec("mkdir", "-p",
                          INSTALL_DIR.rsplit("/", 1)[0])
                sess.exec("git", "clone", REPO_URL, INSTALL_DIR)
            with sess.cd(INSTALL_DIR):
                sess.exec("git", "fetch")
                sess.exec("git", "checkout", COMMIT)
                sess.exec("git", "clean", "-fx")
            with sess.cd(f"{INSTALL_DIR}/libs/libpcache"):
                sess.exec("./build.sh")
            with sess.cd(f"{INSTALL_DIR}/lazyfs"):
                sess.exec("./build.sh")

    def mount(self, sess: Session) -> "LazyFS":
        """Creates dirs + config and starts the daemon
        (lazyfs.clj:165-195)."""
        with sess.su():
            sess.exec("mkdir", "-p", self.dir)
            sess.exec("mkdir", "-p", self.data_dir)
            sess.exec("touch", self.log_file)
            sess.exec("tee", self.config_file, stdin=self.config())
            with sess.cd(f"{INSTALL_DIR}/lazyfs"):
                sess.exec(
                    "scripts/mount-lazyfs.sh",
                    "-c", self.config_file,
                    "-m", self.dir,
                    "-r", self.data_dir,
                )
        return self

    def mounted(self, sess: Session) -> bool:
        res = sess.exec_star("findmnt", self.dir)
        return res.get("exit") == 0 and "lazyfs" in (res.get("out") or "")

    def umount(self, sess: Session) -> None:
        """Stops lazyfs and destroys its state (lazyfs.clj:198-217)."""
        with sess.su():
            try:
                self.lose_unfsynced_writes(sess)
            except Exception:  # noqa: BLE001 — best effort, like `meh`
                pass
            sess.exec_star("fusermount", "-uz", self.dir)
            sess.exec("rm", "-rf", self.lazyfs_dir)

    # -- faults -----------------------------------------------------------

    def send_fifo(self, sess: Session, cmd: str) -> None:
        """Sends a command to the lazyfs control fifo
        (lazyfs.clj:219-228)."""
        sess.exec("bash", "-c", f"echo {cmd} > {self.fifo}",
                  timeout=10)

    def lose_unfsynced_writes(self, sess: Session) -> None:
        """Drop the page cache: un-fsynced writes are gone
        (lazyfs.clj:230-238)."""
        log.info("lazyfs: losing un-fsynced writes under %s", self.dir)
        self.send_fifo(sess, "lazyfs::clear-cache")

    def checkpoint(self, sess: Session) -> None:
        """Sync everything to the backing fs (lazyfs::cache-checkpoint)."""
        self.send_fifo(sess, "lazyfs::cache-checkpoint")


class LazyFSDB(jdb.DB):
    """Wraps a DB so its data directory rides a lazyfs mount; composes
    setup/teardown and exposes the lazyfs log (lazyfs.clj DB record)."""

    def __init__(self, db: jdb.DB, lazyfs: LazyFS):
        self.db = db
        self.lazyfs = lazyfs

    def setup(self, test: dict, sess: Session, node: str) -> None:
        self.lazyfs.install(sess)
        self.lazyfs.mount(sess)
        self.db.setup(test, sess, node)

    def teardown(self, test: dict, sess: Session, node: str) -> None:
        self.db.teardown(test, sess, node)
        self.lazyfs.umount(sess)

    def log_files(self, test: dict, sess: Session, node: str):
        files = list(self.db.log_files(test, sess, node) or [])
        files.append(self.lazyfs.log_file)
        return files

    def lose_unfsynced_writes(self, test: dict, sess: Session,
                              node: str) -> None:
        self.lazyfs.lose_unfsynced_writes(sess)

    # Delegate the capability protocols so Kill/Pause sniffing still
    # sees the inner DB (db.clj:16-33).
    def kill(self, test, sess, node):
        return self.db.kill(test, sess, node)

    def start(self, test, sess, node):
        return self.db.start(test, sess, node)

    def pause(self, test, sess, node):
        return self.db.pause(test, sess, node)

    def resume(self, test, sess, node):
        return self.db.resume(test, sess, node)

    def primaries(self, test):
        return self.db.primaries(test)


class LazyFSNemesis(Nemesis):
    """Injects lose-unfsynced-writes on nodes whose DB rides lazyfs.
    Usually composed right after a kill so the crash also eats the page
    cache, like a power failure."""

    def invoke(self, test: dict, op: Op) -> Op:
        db = test["db"]
        if not hasattr(db, "lose_unfsynced_writes"):
            return op.replace(value="db has no lazyfs")
        nodes = op.value if isinstance(op.value, list) else None

        def act(sess: Session, node: str):
            db.lose_unfsynced_writes(test, sess, node)
            return "lost"

        return op.replace(value=on_nodes(test, act, nodes))

    def fs(self) -> set:
        return {"lose-unfsynced-writes"}


def lazyfs_package(opts: dict) -> Optional[dict]:
    """Nemesis package: periodically drop un-fsynced writes
    ({"faults": {"lazyfs", ...}})."""
    if "lazyfs" not in (opts.get("faults") or set()):
        return None
    from .generator.core import cycle, sleep as gen_sleep

    interval = opts.get("interval", 10.0)
    return {
        "nemesis": LazyFSNemesis(),
        "generator": cycle([
            gen_sleep(interval),
            {"type": "info", "f": "lose-unfsynced-writes", "value": None},
        ]),
        "final-generator": None,
        "perf": [{"name": "lazyfs", "start": {"lose-unfsynced-writes"},
                  "stop": set()}],
    }
