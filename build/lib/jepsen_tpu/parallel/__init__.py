"""Parallelism over the TPU mesh: per-key independent checking (the
`jepsen.independent` equivalent, with keys sharded across devices) and
mesh helpers."""

from .independent import (
    KV,
    IndependentChecker,
    history_keys,
    independent_checker,
    kv,
    subhistories,
    tuple_gen,
)
from .mesh import checker_mesh, default_mesh

__all__ = [
    "KV",
    "IndependentChecker",
    "history_keys",
    "independent_checker",
    "kv",
    "subhistories",
    "tuple_gen",
    "checker_mesh",
    "default_mesh",
]
