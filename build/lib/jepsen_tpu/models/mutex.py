"""Mutex model (knossos.model/mutex; listed in SURVEY.md §2.4 as a model
the rebuild must provide, exercised by BASELINE.json config 2)."""

from __future__ import annotations

from typing import Optional

from ..history.core import Op
from ..history.packed import NIL, Interner
from .base import Model, PackedModel, inconsistent

F_ACQUIRE, F_RELEASE = 0, 1


class Mutex(Model):
    __slots__ = ("locked", "_packed_cache")

    def __init__(self, locked: bool = False):
        self.locked = locked

    def step(self, op: Op):
        if op.f == "acquire":
            if self.locked:
                return inconsistent("cannot acquire held mutex")
            return Mutex(True)
        if op.f == "release":
            if not self.locked:
                return inconsistent("cannot release free mutex")
            return Mutex(False)
        return inconsistent(f"unknown op f {op.f!r}")

    def __eq__(self, other):
        return type(other) is Mutex and other.locked == self.locked

    def __hash__(self):
        return hash(("Mutex", self.locked))

    def __repr__(self):
        return f"Mutex(locked={self.locked})"

    def _compile_packed(self) -> PackedModel:
        interner = Interner()
        interner.intern(None)
        init = (1 if self.locked else 0,)

        def encode(inv: Op, comp: Optional[Op]):
            if inv.f == "acquire":
                return (F_ACQUIRE, NIL, NIL)
            if inv.f == "release":
                return (F_RELEASE, NIL, NIL)
            raise ValueError(f"mutex can't encode op f {inv.f!r}")

        def py_step(state, f, a0, a1):
            held = state[0]
            if f == F_ACQUIRE:
                return (1,), held == 0
            return (0,), held == 1

        def jax_step(state, f, a0, a1):
            import jax.numpy as jnp

            held = state[0]
            is_acq = f == F_ACQUIRE
            # where() rather than &~: `f` may be a plain Python int
            # (tests, py callers), and ~bool is deprecated.
            legal = jnp.where(is_acq, held == 0, held == 1)
            new = jnp.where(is_acq, 1, 0)
            return state.at[0].set(new), legal

        def jax_step_rows(states, f, a0, a1):
            # Scatter-free lane-major form for the Pallas sweep
            # (states is (1, B)).
            import jax.numpy as jnp

            held = states[0]
            is_acq = f == F_ACQUIRE
            # int32 legality: Mosaic fails to legalize selects that
            # produce bool vectors (see _make_pallas_sweep).
            legal = jnp.where(
                is_acq,
                (held == 0).astype(jnp.int32),
                (held == 1).astype(jnp.int32),
            )
            new = jnp.where(is_acq, 1, 0)
            return jnp.broadcast_to(new, held.shape)[None, :], legal

        def describe_op(f: int, a0: int, a1: int) -> str:
            return "acquire" if f == F_ACQUIRE else "release"

        return PackedModel(
            name="mutex",
            state_width=1,
            init_state=init,
            encode=encode,
            py_step=py_step,
            jax_step=jax_step,
            interner=interner,
            describe_op=describe_op,
            jax_step_rows=jax_step_rows,
        )


def mutex() -> Mutex:
    return Mutex(False)
