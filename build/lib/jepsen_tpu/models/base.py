"""Sequential specification models.

Equivalent of the external `knossos.model` namespace as the reference
consumes it (SURVEY.md §2.4; protocol quoted in
/root/reference/doc/tutorial/04-checker.md — `Model`/`step`, inconsistent
states): a model is an immutable value; `step(op)` returns the next model
or an `Inconsistent` describing why the transition is illegal.

TPU-first addition: every checkable model can also compile itself to a
`PackedModel` — a table-free arithmetic transition function over int32
state vectors, usable both as plain Python (CPU reference WGL) and as a
JAX function vmapped over search frontiers (ops/wgl.py).  Op payloads are
interned to int32 by the model's encoder (history/packed.py).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Optional

from ..history.core import OK, Op
from ..history.packed import NIL, Interner, OpEncoderFn


class Inconsistent:
    """Terminal model state: the op sequence was illegal."""

    __slots__ = ("msg",)

    def __init__(self, msg: str):
        self.msg = msg

    def step(self, op: Op) -> "Inconsistent":
        return self

    @property
    def is_inconsistent(self) -> bool:
        return True

    def __repr__(self) -> str:
        return f"Inconsistent({self.msg!r})"

    def __eq__(self, other: object) -> bool:
        return isinstance(other, Inconsistent) and other.msg == self.msg

    def __hash__(self) -> int:
        return hash(("Inconsistent", self.msg))


def inconsistent(msg: str) -> Inconsistent:
    return Inconsistent(msg)


class Model:
    """Base sequential datatype model (knossos.model/Model)."""

    @property
    def is_inconsistent(self) -> bool:
        return False

    def step(self, op: Op) -> "Model | Inconsistent":
        raise NotImplementedError

    # -- packed / device compilation --------------------------------------

    def packed(self) -> "PackedModel":
        """The packed int32 form of this model, memoized per instance —
        device kernel caches key on the identity of the PackedModel's
        jax_step, so repeated checks with one model must reuse one
        compilation.  Raises NotImplementedError for host-only models
        (e.g. unbounded sets)."""
        cached = getattr(self, "_packed_cache", None)
        if cached is None:
            cached = self._compile_packed()
            try:
                object.__setattr__(self, "_packed_cache", cached)
            except AttributeError:
                pass  # __slots__ without cache slot: recompile each call
        return cached

    def _compile_packed(self) -> "PackedModel":
        """Builds the packed form.  Subclasses override this, not
        packed()."""
        raise NotImplementedError(
            f"{type(self).__name__} has no packed/device form"
        )


@dataclass
class PackedModel:
    """A model compiled for the packed/device pipeline.

    - `state_width`: number of int32 words of model state per search
      configuration (1 for cas-register, K for multi-register, ...).
    - `init_state`: tuple of `state_width` ints.
    - `encode`: OpEncoderFn packing (invocation, completion) → (f, a0, a1),
      or None to drop no-effect indeterminate ops.
    - `py_step(state, f, a0, a1) -> (state', legal)`: plain-Python
      transition over int tuples (CPU reference WGL).
    - `jax_step(state, f, a0, a1) -> (state', legal)`: the same transition
      written in jnp over an (state_width,) int32 array — MUST be
      vmap/jit-compatible: no Python control flow on traced values.
    - `interner`: maps packed value codes back to real values for
      counterexample reporting.
    """

    name: str
    state_width: int
    init_state: tuple[int, ...]
    encode: OpEncoderFn
    py_step: Callable[[tuple[int, ...], int, int, int], tuple[tuple[int, ...], bool]]
    jax_step: Callable[..., Any]
    interner: Interner
    #: optional pretty-printer for a packed op row
    describe_op: Optional[Callable[[int, int, int], str]] = None
    #: optional soundness gate: given the PackedOps about to be
    #: searched, return None when the packed form is exact for this
    #: history, or a reason string when it is not (e.g. a bounded-
    #: capacity queue whose capacity the history could exceed) — the
    #: checker then falls back to the host-model search.
    validate_packed: Optional[Callable[..., Optional[str]]] = None
    #: optional batched transition `(states (state_width, B) i32, f,
    #: a0, a1) -> (states', legal (B,))` — LANE-MAJOR (beam lanes on
    #: the trailing axis) and written WITHOUT scatter ops (no
    #: `.at[...].set` — use masked `jnp.where` over rows): the Pallas
    #: witness sweep (ops/wgl_witness.py) lowers this through Mosaic,
    #: which rejects the scatters `vmap(jax_step)` produces and
    #: sub-32-bit / lane<->sublane relayouts.  Models without one
    #: simply stay on the XLA-scan sweep.
    jax_step_rows: Optional[Callable[..., Any]] = None
    #: optional columnar facets for the sound non-linearizability
    #: screens (checker/refute.py): PackedOps -> RefuteView.  Models
    #: without a register-like assert/produce structure leave it None
    #: and skip the screens.
    refute_view: Optional[Callable[..., Any]] = None


def intern_value(interner: Interner, v: Any) -> int:
    """Interns an op payload value to an int32 code.  Hashable required;
    unhashable payloads (lists) are converted to tuples."""
    if isinstance(v, list):
        v = tuple(v)
    return interner.intern(v)
