"""Sequential specification models (knossos.model equivalents) plus their
packed/device compilations for the TPU WGL search."""

from .base import Inconsistent, Model, PackedModel, inconsistent
from .collections import (
    FIFOQueue,
    SetModel,
    UnorderedQueue,
    fifo_queue,
    set_model,
    unordered_queue,
)
from .mutex import Mutex, mutex
from .registers import (
    CASRegister,
    MultiRegister,
    Register,
    cas_register,
    multi_register,
    register,
)

__all__ = [
    "Inconsistent",
    "Model",
    "PackedModel",
    "inconsistent",
    "CASRegister",
    "MultiRegister",
    "Register",
    "cas_register",
    "multi_register",
    "register",
    "Mutex",
    "mutex",
    "FIFOQueue",
    "SetModel",
    "UnorderedQueue",
    "fifo_queue",
    "set_model",
    "unordered_queue",
]
