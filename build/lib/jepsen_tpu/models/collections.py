"""Collection models: set, unordered-queue, FIFO queue.

Host-only knossos.model equivalents (SURVEY.md §2.4).  These back the
generic `linearizable` checker for collection workloads; the cheap
specialized checkers (checker.set / checker.queue / checker.total_queue)
don't need a model at all, mirroring the reference split
(checker.clj:235-287, 648-708).

These models carry unbounded Python collections.  UnorderedQueue and
FIFOQueue have bounded packed int32 forms (capacity-gated, see the
UnorderedQueue docstring); SetModel has none — `packed()` raises and
the linearizable checker falls back to the host-model search.
"""

from __future__ import annotations

from typing import Any, FrozenSet, Tuple

from ..history.core import Op
from .base import Model, inconsistent


def _freeze(v: Any) -> Any:
    if isinstance(v, list):
        return tuple(v)
    if isinstance(v, set):
        return frozenset(v)
    return v


class SetModel(Model):
    """A grow-only set: `add` elements, `read` the full contents."""

    __slots__ = ("items",)

    def __init__(self, items: FrozenSet[Any] = frozenset()):
        self.items = frozenset(items)

    def step(self, op: Op):
        if op.f == "add":
            return SetModel(self.items | {_freeze(op.value)})
        if op.f == "read":
            if op.value is None:
                return self
            got = frozenset(_freeze(x) for x in op.value)
            if got == self.items:
                return self
            return inconsistent(
                f"read {sorted(map(repr, got))} but set contained "
                f"{sorted(map(repr, self.items))}"
            )
        return inconsistent(f"unknown op f {op.f!r}")

    def __eq__(self, other):
        return type(other) is SetModel and other.items == self.items

    def __hash__(self):
        return hash(("SetModel", self.items))

    def __repr__(self):
        return f"SetModel({sorted(map(repr, self.items))})"


class UnorderedQueue(Model):
    """A queue where dequeue may return any enqueued-but-not-dequeued
    element (knossos.model/unordered-queue).

    Device form: a bounded multiset of `packed_capacity` int32 slots
    (0 = empty), kept sorted for canonical equality.  The packed form
    is exact only when the history can never hold more than
    capacity elements; `validate_packed` checks a sound upper bound
    (enqueues invoked so far minus dequeues completed so far, maxed
    over the walk) and the checker falls back to the host model when
    it could bind.  Indeterminate dequeues with unknown values have no
    deterministic packed transition, so packing such histories raises
    and likewise falls back."""

    __slots__ = ("pending", "_packed_cache")
    packed_capacity = 32

    def __init__(self, pending: Tuple[Any, ...] = ()):
        self.pending = tuple(pending)

    def step(self, op: Op):
        v = _freeze(op.value)
        if op.f == "enqueue":
            return UnorderedQueue(self.pending + (v,))
        if op.f == "dequeue":
            if v in self.pending:
                i = self.pending.index(v)
                return UnorderedQueue(self.pending[:i] + self.pending[i + 1 :])
            return inconsistent(f"can't dequeue {v!r}: not in queue")
        return inconsistent(f"unknown op f {op.f!r}")

    def __eq__(self, other):
        return type(other) is UnorderedQueue and sorted(
            map(repr, other.pending)
        ) == sorted(map(repr, self.pending))

    def __hash__(self):
        return hash(("UnorderedQueue", tuple(sorted(map(repr, self.pending)))))

    def __repr__(self):
        return f"UnorderedQueue({list(self.pending)!r})"

    def _compile_packed(self):
        return _queue_packed(self.pending, self.packed_capacity, fifo=False)


def _queue_packed(initial, capacity: int, *, fifo: bool):
    """Shared packed form for the bounded queues: `capacity` int32
    slots, 0 = empty.  Unordered keeps the multiset sorted for
    canonical equality; FIFO keeps insertion order left-aligned.  See
    UnorderedQueue's docstring for the soundness gates."""
    from ..history.core import OK
    from ..history.packed import NIL, Interner
    from .base import PackedModel, intern_value

    C = capacity
    initial = tuple(initial)
    if len(initial) > C:
        raise NotImplementedError("initial queue exceeds capacity")
    interner = Interner()
    interner.intern(None)  # reserve id 0 -> code 1 for None
    F_ENQ, F_DEQ = 0, 1

    def code(v):
        return intern_value(interner, _freeze(v)) + 1  # 0 = empty

    def encode(inv, comp):
        if inv.f == "enqueue":
            return (F_ENQ, code(inv.value), NIL)
        if inv.f == "dequeue":
            if comp is None or comp.type != OK:
                raise ValueError(
                    "indeterminate dequeue has no packed form"
                )
            return (F_DEQ, code(comp.value), NIL)
        raise ValueError(f"queue model can't encode f {inv.f!r}")

    codes = [code(x) for x in initial]
    if fifo:
        init_state = tuple(codes + [0] * (C - len(codes)))
    else:
        init_state = tuple([0] * (C - len(codes)) + sorted(codes))

    def py_step(state, f, a0, a1):
        s = list(state)
        if fifo:
            if f == F_ENQ:
                if 0 not in s:
                    return state, False
                s[s.index(0)] = a0
                return tuple(s), True
            if s[0] != a0 or a0 == 0:
                return state, False
            return tuple(s[1:] + [0]), True
        if f == F_ENQ:
            if 0 not in s:
                return state, False
            s[s.index(0)] = a0
            return tuple(sorted(s)), True
        if a0 not in s:
            return state, False
        s.remove(a0)
        return tuple(sorted([0] + s)), True

    def jax_step(state, f, a0, a1):
        import jax.numpy as jnp

        is_enq = f == F_ENQ
        if fifo:
            # Left-aligned: first zero is the tail slot.
            length = (state != 0).sum()
            has_room = length < C
            enq = state.at[jnp.clip(length, 0, C - 1)].set(a0)
            head_ok = (state[0] == a0) & (a0 != 0)
            deq = jnp.roll(state, -1).at[C - 1].set(0)
            legal = jnp.where(is_enq, has_room, head_ok)
            new = jnp.where(
                is_enq,
                jnp.where(has_room, enq, state),
                jnp.where(head_ok, deq, state),
            )
            return new, legal
        has_room = (state == 0).any()
        enq = state.at[jnp.argmin(state)].set(a0)
        eq = state == a0
        present = eq.any()
        deq = jnp.where(
            jnp.arange(state.shape[0]) == jnp.argmax(eq), 0, state
        )
        legal = jnp.where(is_enq, has_room, present)
        new = jnp.where(is_enq, enq, jnp.where(present, deq, state))
        return jnp.sort(new), legal

    def jax_step_rows(states, f, a0, a1):
        # Scatter-free lane-major FIFO step for the Pallas sweep
        # (states is (C, B), left-aligned): the enqueue slot is picked
        # by a row-iota mask, dequeue is a static one-row shift.
        import jax
        import jax.numpy as jnp

        is_enq = f == F_ENQ
        nonzero = (states != 0).astype(jnp.int32)
        length = nonzero.sum(axis=0)                      # (B,)
        has_room = (length < C).astype(jnp.int32)
        row = jax.lax.broadcasted_iota(jnp.int32, (C, 1), 0)
        slot = row == length[None, :]                     # (C, B)
        # length == C matches no row, so a full lane keeps its state.
        enq = jnp.where(slot, a0, states)
        head_ok = ((states[0] == a0) & (a0 != 0)).astype(jnp.int32)
        deq = jnp.concatenate(
            [states[1:], jnp.zeros((1, states.shape[1]), jnp.int32)],
            axis=0,
        )
        legal = jnp.where(is_enq, has_room, head_ok)
        new = jnp.where(
            is_enq, enq,
            jnp.where((head_ok != 0)[None, :], deq, states),
        )
        return new, legal

    def jax_step_rows_unordered(states, f, a0, a1):
        # Sort-free lane-major multiset step: enqueue fills the first
        # zero row, dequeue clears the first row matching a0 — both
        # picked with a cumulative-count mask instead of argmin/argmax
        # gathers.  The resulting state is NOT kept sorted; that is
        # sound because enqueue/dequeue legality is order-independent
        # and canonical (sorted) form is only needed for the heavy
        # rounds' state dedup — whose inputs are jax_step outputs,
        # which re-sort unconditionally.  Unsorted states therefore
        # only pass through the sweep, never reach a dedup compare.
        import jax.numpy as jnp

        is_enq = f == F_ENQ
        zero_i = (states == 0).astype(jnp.int32)
        first_zero = (jnp.cumsum(zero_i, axis=0) == 1) & (states == 0)
        has_room = zero_i.max(axis=0)                     # (B,) 0/1
        enq = jnp.where(first_zero, a0, states)
        match_i = (states == a0).astype(jnp.int32)
        first_match = (jnp.cumsum(match_i, axis=0) == 1) & (
            states == a0
        )
        present = match_i.max(axis=0)                     # (B,) 0/1
        deq = jnp.where(first_match, 0, states)
        legal = jnp.where(is_enq, has_room, present)
        new = jnp.where(
            is_enq, enq,
            jnp.where((present != 0)[None, :], deq, states),
        )
        return new, legal

    def validate_packed(packed) -> "str | None":
        # Sound size bound at any linearization point t: every enqueue
        # invoked by t could be in the queue; dequeues completed by t
        # must already be linearized (removed).
        size = len(initial)
        worst = size
        events = []  # (when, +1 enq-invoked / -1 deq-completed)
        for i in range(packed.n):
            if packed.f[i] == F_ENQ:
                events.append((int(packed.inv[i]), 1))
            else:
                events.append((int(packed.ret[i]), -1))
        for _, delta in sorted(events):
            size += delta
            worst = max(worst, size)
        if worst > C:
            return (
                f"history may hold {worst} elements; packed "
                f"capacity is {C}"
            )
        return None

    def describe_op(f, a0, a1):
        v = interner.value(a0 - 1) if a0 > 0 else "?"
        return ("enqueue " if f == F_ENQ else "dequeue -> ") + repr(v)

    return PackedModel(
        name="fifo-queue" if fifo else "unordered-queue",
        state_width=C,
        init_state=init_state,
        encode=encode,
        py_step=py_step,
        jax_step=jax_step,
        interner=interner,
        describe_op=describe_op,
        validate_packed=validate_packed,
        jax_step_rows=(jax_step_rows if fifo
                       else jax_step_rows_unordered),
    )


class FIFOQueue(Model):
    """A strict FIFO queue: dequeue must return the head.  Device form:
    left-aligned bounded slots with the same capacity/indeterminate
    gates as UnorderedQueue."""

    __slots__ = ("items", "_packed_cache")
    packed_capacity = 32

    def __init__(self, items: Tuple[Any, ...] = ()):
        self.items = tuple(items)

    def _compile_packed(self):
        return _queue_packed(self.items, self.packed_capacity, fifo=True)

    def step(self, op: Op):
        v = _freeze(op.value)
        if op.f == "enqueue":
            return FIFOQueue(self.items + (v,))
        if op.f == "dequeue":
            if not self.items:
                return inconsistent(f"can't dequeue {v!r} from empty queue")
            if self.items[0] == v:
                return FIFOQueue(self.items[1:])
            return inconsistent(
                f"dequeued {v!r} but head was {self.items[0]!r}"
            )
        return inconsistent(f"unknown op f {op.f!r}")

    def __eq__(self, other):
        return type(other) is FIFOQueue and other.items == self.items

    def __hash__(self):
        return hash(("FIFOQueue", self.items))

    def __repr__(self):
        return f"FIFOQueue({list(self.items)!r})"


def set_model() -> SetModel:
    return SetModel()


def unordered_queue() -> UnorderedQueue:
    return UnorderedQueue()


def fifo_queue() -> FIFOQueue:
    return FIFOQueue()
