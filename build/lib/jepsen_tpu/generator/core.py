"""Pure-functional operation generators.

Equivalent of /root/reference/jepsen/src/jepsen/generator.clj: a
generator is an immutable value asked for operations by the interpreter.
`gen_op(gen, test, ctx)` yields `(op, gen')` where op is an Op or
PENDING, or None when exhausted; `gen_update(gen, test, ctx, event)`
folds an invocation/completion event back into the generator.

Default implementations (generator.clj:561-642):
  * None         — exhausted.
  * dict         — a one-shot op template: fills type/process/time from
                   the context (fill_in_op, generator.clj:500-537).
  * callable     — called (with (test, ctx) or no args) to produce a
                   generator; exhausted generators re-invoke the fn.
  * list/tuple   — runs each element generator in order; updates go to
                   the head.
  * DelayedGen   — evaluated lazily once, first time it could yield.
  * PromiseGen   — PENDING until delivered.

The full combinator catalogue of SURVEY.md §2.2 follows.  Randomness
(soonest-tie-breaking, mix, stagger) flows through a module RNG seedable
via set_rng_seed for deterministic tests (the reference rebinds
rand-int with seed 45100, generator/test.clj:40-52).
"""

from __future__ import annotations

import random
from typing import Any, Callable, Optional, Sequence

from ..history.core import Op
from .context import Context, all_but, make_thread_filter

# ---------------------------------------------------------------------------
# Protocol
# ---------------------------------------------------------------------------


class _Pending:
    _instance: "_Pending | None" = None

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
        return cls._instance

    def __repr__(self) -> str:
        return "PENDING"


#: Sentinel: the generator may yield an op later, but not now.
PENDING = _Pending()

_rng = random.Random()


def set_rng_seed(seed: Optional[int]) -> None:
    """Seeds generator-internal randomness (tie-breaking, mix, stagger)
    for reproducible schedules."""
    global _rng
    _rng = random.Random(seed)


def get_rng() -> random.Random:
    """The module RNG; nemesis partition choices draw from it too, so a
    single set_rng_seed reproduces the whole run."""
    return _rng


class Generator:
    """Base class for explicit generators.  Subclasses are immutable:
    op/update return fresh instances."""

    def op(self, test: dict, ctx: Context):
        """-> (op_or_PENDING, gen') | None."""
        raise NotImplementedError

    def update(self, test: dict, ctx: Context, event: Op) -> "Generator":
        return self


def gen_op(gen: Any, test: dict, ctx: Context):
    """Protocol dispatch for `op` over raw values and Generators."""
    if gen is None:
        return None
    if isinstance(gen, Generator):
        return gen.op(test, ctx)
    return _coerce(gen).op(test, ctx)


def gen_update(gen: Any, test: dict, ctx: Context, event: Op):
    """Protocol dispatch for `update`."""
    if gen is None:
        return None
    if isinstance(gen, Generator):
        return gen.update(test, ctx, event)
    return _coerce(gen).update(test, ctx, event)


def _coerce(gen: Any) -> Generator:
    if isinstance(gen, Generator):
        return gen
    if isinstance(gen, dict):
        return MapGen(gen)
    if callable(gen):
        return FnGen(gen)
    if isinstance(gen, (list, tuple)):
        return SeqGen.of(gen)
    raise TypeError(f"{gen!r} is not a generator")


def fill_in_op(op: dict, ctx: Context):
    """Fills :type (invoke), :process (some free process), and :time
    (context time) into an op template; PENDING if no process is free
    (generator.clj:500-537).  Unknown keys land in Op.ext."""
    p = ctx.some_free_process()
    if p is None:
        return PENDING
    ext = {
        k: v
        for k, v in op.items()
        if k not in ("time", "type", "process", "f", "value")
    }
    return Op(
        type=op.get("type", "invoke"),
        f=op.get("f"),
        value=op.get("value"),
        process=op.get("process", p),
        time=op.get("time", ctx.time),
        index=-1,
        ext=ext,
    )


# ---------------------------------------------------------------------------
# Default implementations
# ---------------------------------------------------------------------------


class MapGen(Generator):
    """A dict is a one-shot op template (generator.clj:566-570)."""

    __slots__ = ("template",)

    def __init__(self, template: dict):
        self.template = template

    def op(self, test, ctx):
        op = fill_in_op(self.template, ctx)
        return (op, self if op is PENDING else None)

    def __repr__(self) -> str:
        return f"MapGen({self.template!r})"


class FnGen(Generator):
    """A function produces a generator when called; that generator runs
    to exhaustion, then the function is called again
    (generator.clj:536-558)."""

    __slots__ = ("f", "_arity2")

    def __init__(self, f: Callable):
        self.f = f
        try:
            import inspect

            n = len(inspect.signature(f).parameters)
        except (TypeError, ValueError):
            n = 0
        self._arity2 = n >= 2

    def op(self, test, ctx):
        produced = self.f(test, ctx) if self._arity2 else self.f()
        if produced is None:
            return None
        return gen_op([produced, self], test, ctx)

    def __repr__(self) -> str:
        return f"FnGen({self.f!r})"


class SeqGen(Generator):
    """Runs element generators in order; updates reach the head only
    (generator.clj:584-612)."""

    __slots__ = ("head", "rest")

    def __init__(self, head: Any, rest: tuple):
        self.head = head
        self.rest = rest

    @staticmethod
    def of(items: Sequence) -> "SeqGen | None":
        items = tuple(items)
        if not items:
            return None
        return SeqGen(items[0], items[1:])

    def op(self, test, ctx):
        head, rest = self.head, self.rest
        while True:
            r = gen_op(head, test, ctx)
            if r is not None:
                op, g2 = r
                if rest:
                    return (op, SeqGen(g2, rest))
                return (op, g2)
            if not rest:
                return None
            head, rest = rest[0], rest[1:]

    def update(self, test, ctx, event):
        return SeqGen(gen_update(self.head, test, ctx, event), self.rest)

    def __repr__(self) -> str:
        return f"SeqGen({self.head!r} +{len(self.rest)})"


class DelayedGen(Generator):
    """Evaluates a thunk to a generator the first time it could produce
    an op (Clojure delay semantics, generator.clj:374-377)."""

    __slots__ = ("thunk", "_cell")

    def __init__(self, thunk: Callable[[], Any]):
        self.thunk = thunk
        self._cell: list = [False, None]

    def _force(self):
        if not self._cell[0]:
            self._cell[0] = True
            self._cell[1] = self.thunk()
        return self._cell[1]

    def op(self, test, ctx):
        return gen_op(self._force(), test, ctx)

    def update(self, test, ctx, event):
        return self


def delayed(thunk: Callable[[], Any]) -> DelayedGen:
    return DelayedGen(thunk)


class PromiseGen(Generator):
    """PENDING until delivered, then acts as the delivered generator
    (promise semantics, generator.clj:622-642)."""

    __slots__ = ("_box",)

    def __init__(self, box: Optional[list] = None):
        self._box = box if box is not None else [False, None]

    def deliver(self, gen: Any) -> None:
        self._box[1] = gen
        self._box[0] = True

    @property
    def realized(self) -> bool:
        return self._box[0]

    def op(self, test, ctx):
        if not self._box[0]:
            return (PENDING, self)
        return gen_op(self._box[1], test, ctx)

    def update(self, test, ctx, event):
        return self


def promise() -> PromiseGen:
    return PromiseGen()


# ---------------------------------------------------------------------------
# Wrappers: validate / exceptions / trace / map / filter
# ---------------------------------------------------------------------------

VALID_OP_TYPES = ("invoke", "info", "sleep", "log")


class InvalidOp(Exception):
    pass


class Validate(Generator):
    """Checks well-formedness of emitted ops: proper tuple shape, known
    type, numeric time, a process that is actually free
    (generator.clj:644-699)."""

    __slots__ = ("gen",)

    def __init__(self, gen: Any):
        self.gen = gen

    def op(self, test, ctx):
        r = gen_op(self.gen, test, ctx)
        if r is None:
            return None
        if not (isinstance(r, tuple) and len(r) == 2):
            raise InvalidOp(
                f"generator should return (op, gen') or None, got {r!r}"
            )
        op, g2 = r
        if op is not PENDING:
            problems = []
            if not isinstance(op, Op):
                problems.append("should be PENDING or an Op")
            else:
                if op.type not in VALID_OP_TYPES:
                    problems.append(
                        f"type should be one of {VALID_OP_TYPES}, was {op.type!r}"
                    )
                if not isinstance(op.time, (int, float)):
                    problems.append("time should be a number")
                if op.process is None:
                    problems.append("no process")
                else:
                    thread = ctx.process_to_thread(op.process)
                    if thread is None or not ctx.thread_free(thread):
                        problems.append(f"process {op.process!r} is not free")
            if problems:
                raise InvalidOp(
                    f"invalid op {op!r} from generator {self.gen!r}: "
                    + "; ".join(problems)
                )
        return (op, Validate(g2))

    def update(self, test, ctx, event):
        return Validate(gen_update(self.gen, test, ctx, event))


def validate(gen: Any) -> Validate:
    return Validate(gen)


class FriendlyExceptions(Generator):
    """Wraps op/update exceptions with generator + context detail
    (generator.clj:701-741)."""

    __slots__ = ("gen",)

    def __init__(self, gen: Any):
        self.gen = gen

    def op(self, test, ctx):
        try:
            r = gen_op(self.gen, test, ctx)
        except Exception as e:
            raise RuntimeError(
                f"Generator threw when asked for an operation.\n"
                f"Generator: {self.gen!r}\nContext: {ctx!r}"
            ) from e
        if r is None:
            return None
        op, g2 = r
        return (op, FriendlyExceptions(g2))

    def update(self, test, ctx, event):
        try:
            return FriendlyExceptions(gen_update(self.gen, test, ctx, event))
        except Exception as e:
            raise RuntimeError(
                f"Generator threw when updated with {event!r}.\n"
                f"Generator: {self.gen!r}\nContext: {ctx!r}"
            ) from e


def friendly_exceptions(gen: Any) -> FriendlyExceptions:
    return FriendlyExceptions(gen)


class Trace(Generator):
    """Logs every op/update (generator.clj:743-786)."""

    __slots__ = ("k", "gen")

    def __init__(self, k: Any, gen: Any):
        self.k = k
        self.gen = gen

    def op(self, test, ctx):
        import logging

        log = logging.getLogger("jepsen.generator")
        r = gen_op(self.gen, test, ctx)
        log.info("%s op ctx=%r -> %r", self.k, ctx, r[0] if r else None)
        if r is None:
            return None
        op, g2 = r
        return (op, Trace(self.k, g2))

    def update(self, test, ctx, event):
        import logging

        logging.getLogger("jepsen.generator").info(
            "%s update event=%r", self.k, event
        )
        return Trace(self.k, gen_update(self.gen, test, ctx, event))


def trace(k: Any, gen: Any) -> Trace:
    return Trace(k, gen)


class OpMap(Generator):
    """Transforms emitted ops with f (generator.clj:790-813)."""

    __slots__ = ("f", "gen")

    def __init__(self, f: Callable[[Op], Op], gen: Any):
        self.f = f
        self.gen = gen

    def op(self, test, ctx):
        r = gen_op(self.gen, test, ctx)
        if r is None:
            return None
        op, g2 = r
        return (op if op is PENDING else self.f(op), OpMap(self.f, g2))

    def update(self, test, ctx, event):
        return OpMap(self.f, gen_update(self.gen, test, ctx, event))


def op_map(f: Callable[[Op], Op], gen: Any) -> OpMap:
    return OpMap(f, gen)


def f_map(fmap: dict, gen: Any) -> OpMap:
    """Renames op :f values through a mapping — composing generators for
    composed nemeses (generator.clj:813-833)."""
    return OpMap(lambda op: op.replace(f=fmap.get(op.f, op.f)), gen)


class OpFilter(Generator):
    """Passes only ops matching pred; PENDING/None pass through
    (generator.clj:835-848)."""

    __slots__ = ("pred", "gen")

    def __init__(self, pred: Callable[[Op], bool], gen: Any):
        self.pred = pred
        self.gen = gen

    def op(self, test, ctx):
        gen = self.gen
        while True:
            r = gen_op(gen, test, ctx)
            if r is None:
                return None
            op, g2 = r
            if op is PENDING or self.pred(op):
                return (op, OpFilter(self.pred, g2))
            gen = g2

    def update(self, test, ctx, event):
        return OpFilter(self.pred, gen_update(self.gen, test, ctx, event))


def op_filter(pred: Callable[[Op], bool], gen: Any) -> OpFilter:
    return OpFilter(pred, gen)


class OnUpdate(Generator):
    """Custom update handler: (f this test ctx event) -> generator
    (generator.clj:850-865)."""

    __slots__ = ("f", "gen")

    def __init__(self, f: Callable, gen: Any):
        self.f = f
        self.gen = gen

    def op(self, test, ctx):
        r = gen_op(self.gen, test, ctx)
        if r is None:
            return None
        op, g2 = r
        return (op, OnUpdate(self.f, g2))

    def update(self, test, ctx, event):
        return self.f(self, test, ctx, event)


def on_update(f: Callable, gen: Any) -> OnUpdate:
    return OnUpdate(f, gen)


# ---------------------------------------------------------------------------
# Thread routing
# ---------------------------------------------------------------------------


class OnThreads(Generator):
    """Restricts a generator to threads matching pred; the inner
    generator sees a context filtered to those threads
    (generator.clj:867-892)."""

    __slots__ = ("pred", "ctx_filter", "gen")

    def __init__(self, pred: Any, gen: Any, ctx_filter=None):
        self.pred = pred
        self.ctx_filter = ctx_filter or make_thread_filter(pred)
        self.gen = gen

    def op(self, test, ctx):
        r = gen_op(self.gen, test, self.ctx_filter(ctx))
        if r is None:
            return None
        op, g2 = r
        return (op, OnThreads(self.pred, g2, self.ctx_filter))

    def update(self, test, ctx, event):
        thread = ctx.process_to_thread(event.process)
        p = self.pred
        matches = p(thread) if callable(p) else thread in p
        if matches:
            return OnThreads(
                self.pred,
                gen_update(self.gen, test, self.ctx_filter(ctx), event),
                self.ctx_filter,
            )
        return self


def on_threads(pred: Any, gen: Any) -> OnThreads:
    return OnThreads(pred, gen)


on = on_threads


def clients(client_gen: Any, nemesis_gen: Any = None):
    """Routes ops to client threads only; with a second argument, also
    routes a nemesis generator to the nemesis (generator.clj:1125-1136)."""
    cg = on_threads(all_but("nemesis"), client_gen)
    if nemesis_gen is None:
        return cg
    return any_gen(cg, nemesis(nemesis_gen))


def nemesis(nemesis_gen: Any, client_gen: Any = None):
    """Routes ops to the nemesis thread only; with a second argument,
    also routes a client generator to clients (generator.clj:1138-1147)."""
    ng = on_threads({"nemesis"}, nemesis_gen)
    if client_gen is None:
        return ng
    return any_gen(ng, clients(client_gen))


# ---------------------------------------------------------------------------
# Choice: any / mix / each-thread / reserve
# ---------------------------------------------------------------------------


def soonest_op_map(m1: Optional[dict], m2: Optional[dict]) -> Optional[dict]:
    """Picks whichever {op, weight, ...} map happens sooner; PENDING
    loses to a real op; time ties break randomly, weighted
    (generator.clj:894-938)."""
    if m1 is None:
        return m2
    if m2 is None:
        return m1
    op1, op2 = m1["op"], m2["op"]
    if op1 is PENDING:
        return m2
    if op2 is PENDING:
        return m1
    t1, t2 = op1.time, op2.time
    if t1 == t2:
        w1 = m1.get("weight", 1)
        w2 = m2.get("weight", 1)
        chosen = m1 if _rng.randrange(w1 + w2) < w1 else m2
        return {**chosen, "weight": w1 + w2}
    return m1 if t1 < t2 else m2


class AnyGen(Generator):
    """Ops from whichever generator is soonest; updates go to all
    (generator.clj:940-965)."""

    __slots__ = ("gens",)

    def __init__(self, gens: tuple):
        self.gens = gens

    def op(self, test, ctx):
        soonest = None
        for i, g in enumerate(self.gens):
            r = gen_op(g, test, ctx)
            if r is not None:
                soonest = soonest_op_map(
                    soonest, {"op": r[0], "gen": r[1], "i": i}
                )
        if soonest is None:
            return None
        gens = list(self.gens)
        gens[soonest["i"]] = soonest["gen"]
        return (soonest["op"], AnyGen(tuple(gens)))

    def update(self, test, ctx, event):
        return AnyGen(
            tuple(gen_update(g, test, ctx, event) for g in self.gens)
        )


def any_gen(*gens: Any):
    if not gens:
        return None
    if len(gens) == 1:
        return gens[0]
    return AnyGen(tuple(gens))


class EachThread(Generator):
    """An independent copy of the generator per thread; each copy's
    context contains just that thread (generator.clj:967-1021)."""

    __slots__ = ("fresh", "filters", "gens")

    def __init__(self, fresh: Any, filters: Optional[dict] = None, gens: Optional[dict] = None):
        self.fresh = fresh
        self.filters = filters
        self.gens = gens or {}

    def _filters(self, ctx: Context) -> dict:
        # Lazily compiled once and shared across evolved instances, like
        # the reference's context-filters promise (generator.clj:967-978).
        if self.filters is None:
            self.filters = {
                t: make_thread_filter({t}, ctx) for t in ctx.all_threads()
            }
        return self.filters

    def op(self, test, ctx):
        filters = self._filters(ctx)
        soonest = None
        for thread in ctx.free_threads():
            g = self.gens.get(thread, self.fresh)
            r = gen_op(g, test, filters[thread](ctx))
            if r is not None:
                soonest = soonest_op_map(
                    soonest, {"op": r[0], "gen": r[1], "thread": thread}
                )
        if soonest is not None:
            gens = dict(self.gens)
            gens[soonest["thread"]] = soonest["gen"]
            return (soonest["op"], EachThread(self.fresh, filters, gens))
        if ctx.free_thread_count() != ctx.all_thread_count():
            return (PENDING, self)  # busy threads may free up later
        return None  # every thread exhausted

    def update(self, test, ctx, event):
        filters = self._filters(ctx)
        thread = ctx.process_to_thread(event.process)
        if thread is None or thread not in filters:
            return self
        g = self.gens.get(thread, self.fresh)
        g2 = gen_update(g, test, filters[thread](ctx), event)
        gens = dict(self.gens)
        gens[thread] = g2
        return EachThread(self.fresh, filters, gens)


def each_thread(gen: Any) -> EachThread:
    return EachThread(gen)


class Reserve(Generator):
    """Statically partitions threads into ranges, each with its own
    generator, plus a default for the rest (generator.clj:1023-1121).
    Ranges weight soonest-ties by their size."""

    __slots__ = ("ranges", "filters", "gens")

    def __init__(self, ranges: tuple, filters: tuple, gens: tuple):
        self.ranges = ranges       # tuple of frozensets of threads
        self.filters = filters     # one per range + default last
        self.gens = gens           # one per range + default last

    def op(self, test, ctx):
        soonest = None
        for i, threads in enumerate(self.ranges):
            r = gen_op(self.gens[i], test, self.filters[i](ctx))
            if r is not None:
                soonest = soonest_op_map(
                    soonest,
                    {"op": r[0], "gen": r[1], "weight": len(threads), "i": i},
                )
        dctx = self.filters[-1](ctx)
        r = gen_op(self.gens[-1], test, dctx)
        if r is not None:
            soonest = soonest_op_map(
                soonest,
                {
                    "op": r[0],
                    "gen": r[1],
                    "weight": dctx.all_thread_count(),
                    "i": len(self.ranges),
                },
            )
        if soonest is None:
            return None
        gens = list(self.gens)
        gens[soonest["i"]] = soonest["gen"]
        return (soonest["op"], Reserve(self.ranges, self.filters, tuple(gens)))

    def update(self, test, ctx, event):
        thread = ctx.process_to_thread(event.process)
        i = len(self.ranges)
        for j, threads in enumerate(self.ranges):
            if thread in threads:
                i = j
                break
        gens = list(self.gens)
        gens[i] = gen_update(gens[i], test, self.filters[i](ctx), event)
        return Reserve(self.ranges, self.filters, tuple(gens))


def reserve(*args: Any) -> Reserve:
    """reserve(5, write_gen, 10, cas_gen, read_gen): the first 5 threads
    run write_gen, the next 10 run cas_gen, everyone else the default."""
    if len(args) % 2 != 1:
        raise ValueError("reserve takes count/gen pairs plus a default gen")
    default = args[-1]
    pairs = list(zip(args[:-1:2], args[1:-1:2]))
    ranges = []
    gens = []
    n = 0
    for count, g in pairs:
        ranges.append(frozenset(range(n, n + count)))
        gens.append(g)
        n += count
    all_reserved = frozenset().union(*ranges) if ranges else frozenset()
    filters = tuple(make_thread_filter(r) for r in ranges) + (
        make_thread_filter(lambda t: t not in all_reserved),
    )
    return Reserve(tuple(ranges), filters, tuple(gens) + (default,))


class Mix(Generator):
    """A uniformly random mixture of generators; exhausted members are
    removed (generator.clj:1151-1196).  Ignores updates."""

    __slots__ = ("i", "gens")

    def __init__(self, i: int, gens: tuple):
        self.i = i
        self.gens = gens

    def op(self, test, ctx):
        gens = self.gens
        i = self.i
        while gens:
            r = gen_op(gens[i], test, ctx)
            if r is not None:
                op, g2 = r
                new = list(gens)
                new[i] = g2
                return (op, Mix(_rng.randrange(len(new)), tuple(new)))
            gens = gens[:i] + gens[i + 1 :]
            if gens:
                i = _rng.randrange(len(gens))
        return None


def mix(gens: Sequence) -> Optional[Mix]:
    gens = tuple(gens)
    if not gens:
        return None
    return Mix(_rng.randrange(len(gens)), gens)


# ---------------------------------------------------------------------------
# Bounding: limit / repeat / cycle / process-limit / time-limit
# ---------------------------------------------------------------------------


class Limit(Generator):
    """At most n ops (generator.clj:1199-1205)."""

    __slots__ = ("remaining", "gen")

    def __init__(self, remaining: int, gen: Any):
        self.remaining = remaining
        self.gen = gen

    def op(self, test, ctx):
        if self.remaining <= 0:
            return None
        r = gen_op(self.gen, test, ctx)
        if r is None:
            return None
        op, g2 = r
        return (op, Limit(self.remaining - 1, g2))

    def update(self, test, ctx, event):
        return Limit(self.remaining, gen_update(self.gen, test, ctx, event))


def limit(n: int, gen: Any) -> Limit:
    return Limit(n, gen)


def once(gen: Any) -> Limit:
    return Limit(1, gen)


def log(msg: str) -> dict:
    """An op that logs a message (generator.clj:1210-1214)."""
    return {"type": "log", "value": msg}


class Repeat(Generator):
    """Repeats the underlying generator's next op forever (or n times);
    the underlying generator state does not advance
    (generator.clj:1216-1240)."""

    __slots__ = ("remaining", "gen")

    def __init__(self, remaining: int, gen: Any):
        self.remaining = remaining  # -1 = infinite
        self.gen = gen

    def op(self, test, ctx):
        if self.remaining == 0:
            return None
        r = gen_op(self.gen, test, ctx)
        if r is None:
            return None
        op, _ = r
        return (op, Repeat(max(-1, self.remaining - 1), self.gen))

    def update(self, test, ctx, event):
        return Repeat(self.remaining, gen_update(self.gen, test, ctx, event))


def repeat(gen: Any, n: int = -1) -> Repeat:
    return Repeat(n, gen)


class Cycle(Generator):
    """Resets the generator to its original value when exhausted
    (generator.clj:1242-1270)."""

    __slots__ = ("remaining", "original", "gen")

    def __init__(self, remaining: int, original: Any, gen: Any):
        self.remaining = remaining
        self.original = original
        self.gen = gen

    def op(self, test, ctx):
        remaining, gen = self.remaining, self.gen
        while remaining != 0:
            r = gen_op(gen, test, ctx)
            if r is not None:
                op, g2 = r
                return (op, Cycle(remaining, self.original, g2))
            remaining -= 1
            gen = self.original
        return None

    def update(self, test, ctx, event):
        return Cycle(
            self.remaining,
            self.original,
            gen_update(self.gen, test, ctx, event),
        )


def cycle(gen: Any, n: int = -1) -> Cycle:
    return Cycle(n, gen, gen)


class ProcessLimit(Generator):
    """Stops once ops would involve more than n distinct processes
    (generator.clj:1272-1296) — bounds knossos search width from
    crashed-process churn."""

    __slots__ = ("n", "procs", "gen")

    def __init__(self, n: int, procs: frozenset, gen: Any):
        self.n = n
        self.procs = procs
        self.gen = gen

    def op(self, test, ctx):
        r = gen_op(self.gen, test, ctx)
        if r is None:
            return None
        op, g2 = r
        if op is PENDING:
            return (op, ProcessLimit(self.n, self.procs, g2))
        procs = self.procs | frozenset(ctx.all_processes())
        if len(procs) > self.n:
            return None
        return (op, ProcessLimit(self.n, procs, g2))

    def update(self, test, ctx, event):
        return ProcessLimit(
            self.n, self.procs, gen_update(self.gen, test, ctx, event)
        )


def process_limit(n: int, gen: Any) -> ProcessLimit:
    return ProcessLimit(n, frozenset(), gen)


def secs_to_nanos(s: float) -> int:
    return int(s * 1_000_000_000)


class TimeLimit(Generator):
    """Emits ops for dt seconds after its first op
    (generator.clj:1298-1322)."""

    __slots__ = ("limit", "cutoff", "gen")

    def __init__(self, limit: int, cutoff: Optional[int], gen: Any):
        self.limit = limit
        self.cutoff = cutoff
        self.gen = gen

    def op(self, test, ctx):
        r = gen_op(self.gen, test, ctx)
        if r is None:
            return None
        op, g2 = r
        if op is PENDING:
            return (op, TimeLimit(self.limit, self.cutoff, g2))
        cutoff = self.cutoff if self.cutoff is not None else op.time + self.limit
        if op.time >= cutoff:
            return None
        return (op, TimeLimit(self.limit, cutoff, g2))

    def update(self, test, ctx, event):
        return TimeLimit(
            self.limit, self.cutoff, gen_update(self.gen, test, ctx, event)
        )


def time_limit(dt_secs: float, gen: Any) -> TimeLimit:
    return TimeLimit(secs_to_nanos(dt_secs), None, gen)


# ---------------------------------------------------------------------------
# Timing: stagger / delay / sleep
# ---------------------------------------------------------------------------


class Stagger(Generator):
    """Schedules ops at uniformly random intervals in [0, 2*dt) — a
    total-rate spacing across all threads (generator.clj:1324-1377)."""

    __slots__ = ("dt", "next_time", "gen")

    def __init__(self, dt: int, next_time: Optional[int], gen: Any):
        self.dt = dt
        self.next_time = next_time
        self.gen = gen

    def op(self, test, ctx):
        r = gen_op(self.gen, test, ctx)
        if r is None:
            return None
        op, g2 = r
        if op is PENDING:
            return (op, self)
        next_time = self.next_time if self.next_time is not None else ctx.time
        if next_time <= op.time:
            return (op, Stagger(self.dt, op.time + _rng.randrange(max(1, self.dt)), g2))
        return (
            op.replace(time=next_time),
            Stagger(self.dt, next_time + _rng.randrange(max(1, self.dt)), g2),
        )

    def update(self, test, ctx, event):
        return Stagger(
            self.dt, self.next_time, gen_update(self.gen, test, ctx, event)
        )


def stagger(dt_secs: float, gen: Any) -> Stagger:
    return Stagger(secs_to_nanos(2 * dt_secs), None, gen)


class Delay(Generator):
    """Emits ops exactly dt apart (catching up if behind)
    (generator.clj:1379-1426)."""

    __slots__ = ("dt", "next_time", "gen")

    def __init__(self, dt: int, next_time: Optional[int], gen: Any):
        self.dt = dt
        self.next_time = next_time
        self.gen = gen

    def op(self, test, ctx):
        r = gen_op(self.gen, test, ctx)
        if r is None:
            return None
        op, g2 = r
        if op is PENDING:
            return (op, Delay(self.dt, self.next_time, g2))
        next_time = self.next_time if self.next_time is not None else op.time
        op = op.replace(time=max(op.time, next_time))
        return (op, Delay(self.dt, op.time + self.dt, g2))

    def update(self, test, ctx, event):
        return Delay(
            self.dt, self.next_time, gen_update(self.gen, test, ctx, event)
        )


def delay(dt_secs: float, gen: Any) -> Delay:
    return Delay(secs_to_nanos(dt_secs), None, gen)


def sleep(dt_secs: float) -> dict:
    """Exactly one special op making its receiving process do nothing
    for dt seconds; the worker sleeps and the op is excluded from the
    journal (generator.clj:1428-1432, interpreter.clj:129-131,
    :176-181).  Use repeat(sleep(10)) to sleep repeatedly."""
    return {"type": "sleep", "value": dt_secs}


# ---------------------------------------------------------------------------
# Phasing: synchronize / phases / then / until-ok / flip-flop / cycle-times
# ---------------------------------------------------------------------------


class Synchronize(Generator):
    """PENDING until every thread is free, then becomes the wrapped
    generator (generator.clj:1434-1450)."""

    __slots__ = ("gen",)

    def __init__(self, gen: Any):
        self.gen = gen

    def op(self, test, ctx):
        if ctx.free_thread_count() == ctx.all_thread_count():
            return gen_op(self.gen, test, ctx)
        return (PENDING, self)

    def update(self, test, ctx, event):
        return Synchronize(gen_update(self.gen, test, ctx, event))


def synchronize(gen: Any) -> Synchronize:
    return Synchronize(gen)


def phases(*gens: Any) -> list:
    """Each generator runs to completion, with a barrier between phases
    (generator.clj:1452-1457)."""
    return [Synchronize(g) for g in gens]


def then(a: Any, b: Any) -> list:
    """b, then (after a barrier) a — argument order matches the
    reference's ->>-friendly `then` (generator.clj:1459-1468)."""
    return [b, Synchronize(a)]


class UntilOk(Generator):
    """Emits ops until one completes :ok (generator.clj:1470-1500)."""

    __slots__ = ("gen", "done", "active")

    def __init__(self, gen: Any, done: bool = False, active: frozenset = frozenset()):
        self.gen = gen
        self.done = done
        self.active = active

    def op(self, test, ctx):
        if self.done:
            return None
        r = gen_op(self.gen, test, ctx)
        if r is None:
            return None
        op, g2 = r
        if op is PENDING:
            return (op, UntilOk(g2, self.done, self.active))
        return (op, UntilOk(g2, self.done, self.active | {op.process}))

    def update(self, test, ctx, event):
        g2 = gen_update(self.gen, test, ctx, event)
        p = event.process
        if p in self.active:
            if event.type == "ok":
                return UntilOk(g2, True, self.active - {p})
            if event.type in ("info", "fail"):
                return UntilOk(g2, self.done, self.active - {p})
        return UntilOk(g2, self.done, self.active)


def until_ok(gen: Any) -> UntilOk:
    return UntilOk(gen)


class FlipFlop(Generator):
    """Alternates between generators; stops when any is exhausted
    (generator.clj:1502-1516).  Ignores updates."""

    __slots__ = ("gens", "i")

    def __init__(self, gens: tuple, i: int):
        self.gens = gens
        self.i = i

    def op(self, test, ctx):
        r = gen_op(self.gens[self.i], test, ctx)
        if r is None:
            return None
        op, g2 = r
        gens = list(self.gens)
        gens[self.i] = g2
        return (op, FlipFlop(tuple(gens), (self.i + 1) % len(gens)))


def flip_flop(a: Any, b: Any) -> FlipFlop:
    return FlipFlop((a, b), 0)


class CycleTimes(Generator):
    """Rotates between generators on a timed schedule
    (generator.clj:1518-1608)."""

    __slots__ = ("period", "t0", "intervals", "cutoffs", "gens")

    def __init__(self, period, t0, intervals, cutoffs, gens):
        self.period = period
        self.t0 = t0
        self.intervals = intervals
        self.cutoffs = cutoffs
        self.gens = gens

    def op(self, test, ctx):
        now = ctx.time
        t0 = self.t0 if self.t0 is not None else now
        in_period = (now - t0) % self.period
        cycle_start = now - in_period
        i = 0
        while i < len(self.cutoffs) and in_period >= self.cutoffs[i]:
            i += 1
        t = cycle_start + sum(self.intervals[:i])
        # Walk windows until one contains the op; t grows every step, so
        # this terminates for any positive period.
        while True:
            interval = self.intervals[i]
            t_end = t + interval
            r = gen_op(self.gens[i], test, ctx.with_time(max(now, t)))
            if r is None:
                return None
            op, g2 = r
            gens = list(self.gens)
            gens[i] = g2
            nxt = CycleTimes(self.period, t0, self.intervals, self.cutoffs, tuple(gens))
            if op is PENDING:
                return (PENDING, nxt)
            if op.time < t_end:
                return (op, nxt)
            i = (i + 1) % len(self.gens)
            t = t_end

    def update(self, test, ctx, event):
        return CycleTimes(
            self.period,
            self.t0,
            self.intervals,
            self.cutoffs,
            tuple(gen_update(g, test, ctx, event) for g in self.gens),
        )


def cycle_times(*specs: Any) -> Optional[CycleTimes]:
    """cycle_times(5, writes, 10, reads): writes for 5 s, reads for
    10 s, repeating.  State persists across rotations."""
    if not specs:
        return None
    if len(specs) % 2 != 0:
        raise ValueError("cycle_times takes duration, generator pairs")
    intervals = tuple(secs_to_nanos(d) for d in specs[::2])
    gens = tuple(specs[1::2])
    cutoffs = []
    acc = 0
    for iv in intervals:
        acc += iv
        cutoffs.append(acc)
    return CycleTimes(acc, None, intervals, tuple(cutoffs[:-1] or cutoffs), gens)


def concat(*gens: Any) -> list:
    """Sequential composition — a list is already a generator
    (generator.clj:798-803)."""
    return list(gens)
