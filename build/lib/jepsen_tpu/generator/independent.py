"""Per-key generator lifting — `jepsen.independent`'s generator side.

Equivalent of /root/reference/jepsen/src/jepsen/independent.clj:37-257:
`sequential_generator` runs one key's generator at a time;
`concurrent_generator` splits worker threads into groups of n, each
group working a key to exhaustion before taking the next.  Op values are
wrapped in KV tuples; the checker side (parallel/independent.py) splits
the history back out per key and shards the checking across the TPU
mesh.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, Optional, Sequence

from ..parallel.independent import KV
from .context import Context, make_thread_filter
from .core import (
    PENDING,
    Generator,
    clients,
    gen_op,
    gen_update,
    op_map,
    soonest_op_map,
)


def tuple_gen(k: Any, gen: Any):
    """Wraps a generator so invoke values become [k v] tuples
    (independent.clj:101-109)."""
    return op_map(
        lambda op: op.replace(value=KV(k, op.value))
        if op.type == "invoke"
        else op,
        gen,
    )


def sequential_generator(keys: Iterable[Any], fgen: Callable[[Any], Any]) -> list:
    """One key at a time: exhaust (fgen k1), then (fgen k2), ...
    (independent.clj:37-53)."""
    return [tuple_gen(k, fgen(k)) for k in keys]


class ConcurrentGenerator(Generator):
    """Thread groups of n, each working one key at a time
    (independent.clj:109-230).  Wrap with gen.clients() via
    concurrent_generator() — the nemesis is excluded by design."""

    def __init__(
        self,
        n: int,
        fgen: Callable[[Any], Any],
        keys: tuple,
        gens: Optional[tuple] = None,
        group_threads: Optional[tuple] = None,
        thread_group: Optional[dict] = None,
        filters: Optional[tuple] = None,
    ):
        self.n = n
        self.fgen = fgen
        self.keys = keys
        self.gens = gens
        self.group_threads = group_threads
        self.thread_group = thread_group
        self.filters = filters

    def _init_groups(self, ctx: Context):
        """Lazily partitions sorted threads into groups of n
        (independent.clj:55-99)."""
        threads = sorted(ctx.all_threads(), key=lambda t: (isinstance(t, str), t))
        count = len(threads)
        if self.n > count:
            raise ValueError(
                f"{count} worker threads can't run keys with {self.n}-thread "
                f"groups; raise concurrency to at least {self.n}"
            )
        if count % self.n != 0:
            raise ValueError(
                f"{count} threads don't divide into groups of {self.n}; "
                f"make concurrency a multiple of {self.n}"
            )
        groups = tuple(
            frozenset(threads[i : i + self.n])
            for i in range(0, count, self.n)
        )
        thread_group = {t: g for g, ts in enumerate(groups) for t in ts}
        filters = tuple(make_thread_filter(ts, ctx) for ts in groups)
        return groups, thread_group, filters

    def op(self, test, ctx):
        group_threads = self.group_threads
        thread_group = self.thread_group
        filters = self.filters
        if group_threads is None:
            group_threads, thread_group, filters = self._init_groups(ctx)

        keys = self.keys
        gens = self.gens
        if gens is None:
            g_count = len(group_threads)
            gens = tuple(
                tuple_gen(k, self.fgen(k)) for k in keys[:g_count]
            )
            gens += (None,) * (g_count - len(gens))
            keys = keys[g_count:]

        free_groups = {thread_group[t] for t in ctx.free_threads() if t in thread_group}

        gens = list(gens)
        soonest = None
        for group in free_groups:
            while True:
                g = gens[group]
                if g is None:
                    break
                r = gen_op(g, test, filters[group](ctx))
                if r is not None:
                    op, g2 = r
                    soonest = soonest_op_map(
                        soonest,
                        {
                            "op": op,
                            "group": group,
                            "gen": g2,
                            "weight": len(group_threads[group]),
                        },
                    )
                    break
                # Group's key exhausted: take the next key, or park.
                if keys:
                    k, keys = keys[0], keys[1:]
                    gens[group] = tuple_gen(k, self.fgen(k))
                else:
                    gens[group] = None

        nxt = ConcurrentGenerator(
            self.n,
            self.fgen,
            keys,
            tuple(gens),
            group_threads,
            thread_group,
            filters,
        )
        if soonest is not None and soonest["op"] is not None:
            gens[soonest["group"]] = soonest["gen"]
            nxt = ConcurrentGenerator(
                self.n,
                self.fgen,
                keys,
                tuple(gens),
                group_threads,
                thread_group,
                filters,
            )
            return (soonest["op"], nxt)
        # Busy groups may still produce ops later.
        if any(g is not None for g in gens):
            return (PENDING, nxt)
        return None

    def update(self, test, ctx, event):
        if self.thread_group is None or self.gens is None:
            return self
        thread = ctx.process_to_thread(event.process)
        group = self.thread_group.get(thread)
        if group is None:
            return self
        # Unlift the tuple so the per-key generator sees its own value.
        ev = event
        if isinstance(event.value, KV):
            ev = event.replace(value=event.value.value)
        gens = list(self.gens)
        gens[group] = gen_update(gens[group], test, ctx, ev)
        return ConcurrentGenerator(
            self.n,
            self.fgen,
            self.keys,
            tuple(gens),
            self.group_threads,
            self.thread_group,
            self.filters,
        )


def concurrent_generator(n: int, keys: Sequence[Any], fgen: Callable[[Any], Any]):
    """n threads per group, each group working one key at a time; clients
    only (independent.clj:232-257)."""
    if n <= 0 or not isinstance(n, int):
        raise ValueError("group size must be a positive integer")
    return clients(ConcurrentGenerator(n, fgen, tuple(keys)))
