"""Deterministic generator simulation — the generator test kit.

Equivalent of /root/reference/jepsen/src/jepsen/generator/test.clj:
`simulate` executes a generator against a synthetic completion function
with a fixed RNG seed (45100) and a simulated clock, without real
clients; `quick`, `perfect`, `perfect_info`, `imperfect` are canned
completion models.  This is how every combinator gets unit-tested
(generator_test.clj pattern, SURVEY.md §4.2).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

from ..history.core import Op
from .context import Context
from .core import PENDING, Validate, gen_op, gen_update, set_rng_seed

RAND_SEED = 45100

#: How long perfect operations take, in nanos (generator/test.clj:132).
PERFECT_LATENCY = 10


def n_plus_nemesis_context(n: int) -> Context:
    return Context.for_test({"concurrency": n})


def default_context() -> Context:
    """Two worker threads plus a nemesis (generator/test.clj:25-28)."""
    return n_plus_nemesis_context(2)


def simulate(
    gen: Any,
    complete_fn: Callable[[Context, Op], Op],
    ctx: Optional[Context] = None,
    test: Optional[dict] = None,
    max_ops: int = 1_000_000,
) -> list[Op]:
    """Simulates the full history a generator would produce, given a
    function from (ctx, invocation) to the completion op
    (generator/test.clj:54-113).  Returns invocations and completions
    with indices stripped."""
    set_rng_seed(RAND_SEED)
    ctx = ctx if ctx is not None else default_context()
    test = test or {}
    ops: list[Op] = []
    in_flight: list[Op] = []  # sorted by time
    g = Validate(gen)

    while len(ops) < max_ops:
        r = gen_op(g, test, ctx)
        if r is None:
            ops.extend(in_flight)
            break
        invoke, g2 = r
        if invoke is not PENDING and (
            not in_flight or invoke.time <= in_flight[0].time
        ):
            # Emit the invocation: advance clock, mark busy, update gen,
            # schedule its completion.
            thread = ctx.process_to_thread(invoke.process)
            ctx = ctx.busy_thread(max(ctx.time, invoke.time), thread)
            g = gen_update(g2, test, ctx, invoke)
            complete = complete_fn(ctx, invoke)
            in_flight.append(complete)
            in_flight.sort(key=lambda o: o.time)
            ops.append(invoke)
        else:
            # Pending or future invocation: complete something first.
            if not in_flight:
                raise RuntimeError(
                    f"generator pending but nothing in flight: {g!r}"
                )
            op = in_flight.pop(0)
            thread = ctx.process_to_thread(op.process)
            ctx = ctx.free_thread(op.time, thread)
            g = gen_update(g, test, ctx, op)
            if thread != "nemesis" and op.type == "info":
                ctx = ctx.with_next_process(thread)
            ops.append(op)
    return [o.replace(index=-1) for o in ops]


def invocations(ops: list[Op]) -> list[Op]:
    return [o for o in ops if o.type == "invoke"]


def quick_ops(gen: Any, ctx: Optional[Context] = None) -> list[Op]:
    """Every op succeeds instantly with zero latency."""
    return simulate(gen, lambda c, inv: inv.replace(type="ok"), ctx=ctx)


def quick(gen: Any, ctx: Optional[Context] = None) -> list[Op]:
    return invocations(quick_ops(gen, ctx))


def perfect_ops(gen: Any, ctx: Optional[Context] = None) -> list[Op]:
    """Every op succeeds in 10 ns; returns the full history."""
    return simulate(
        gen,
        lambda c, inv: inv.replace(type="ok", time=inv.time + PERFECT_LATENCY),
        ctx=ctx,
    )


def perfect(gen: Any, ctx: Optional[Context] = None) -> list[Op]:
    return invocations(perfect_ops(gen, ctx))


def perfect_info(gen: Any, ctx: Optional[Context] = None) -> list[Op]:
    """Every op crashes with :info in 10 ns; returns invocations."""
    return invocations(
        simulate(
            gen,
            lambda c, inv: inv.replace(
                type="info", time=inv.time + PERFECT_LATENCY
            ),
            ctx=ctx,
        )
    )


def imperfect(gen: Any, ctx: Optional[Context] = None) -> list[Op]:
    """Threads rotate fail -> info -> ok completions, 10 ns each;
    returns the full history."""
    state: dict = {}
    nxt = {None: "fail", "fail": "info", "info": "ok", "ok": "fail"}

    def complete(c: Context, inv: Op) -> Op:
        t = c.process_to_thread(inv.process)
        state[t] = nxt[state.get(t)]
        return inv.replace(type=state[t], time=inv.time + PERFECT_LATENCY)

    return simulate(gen, complete)
