"""Immutable generator scheduling context.

Equivalent of /root/reference/jepsen/src/jepsen/generator/context.clj
(+ its translation_table.clj): the context tracks the logical time, which
threads exist, which are free, and which process each thread is running.
Thread names are the ints 0..concurrency-1 plus "nemesis"
(context.clj:258-286); each thread initially runs itself as a process,
and a crashed thread's next process id is old + concurrency
(context.clj:240-256).

TPU-era design notes: the reference uses java BitSets + a Bifurcan map;
Python's arbitrary-width ints *are* immutable bitsets with O(1)
clone-free and/or, so thread sets here are plain ints — `free_mask` bit
i set means thread index i is free.  Precompiled thread filters
(make_thread_filter, context.clj:311-358) are just `& mask`.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, Optional

NEMESIS = "nemesis"


def _mask_bits(mask: int) -> Iterable[int]:
    """Indices of set bits, ascending."""
    while mask:
        b = mask & -mask
        yield b.bit_length() - 1
        mask ^= b


class Context:
    """Immutable scheduler state.  All mutation methods return new
    contexts; bit-mask fields make that cheap."""

    __slots__ = (
        "time",
        "next_thread_index",
        "names",
        "_index",
        "int_thread_count",
        "all_mask",
        "free_mask",
        "thread_process",
        "process_thread",
        "ext",
    )

    def __init__(
        self,
        time: int,
        next_thread_index: int,
        names: tuple,
        index: dict,
        int_thread_count: int,
        all_mask: int,
        free_mask: int,
        thread_process: tuple,
        process_thread: dict,
        ext: dict,
    ):
        self.time = time
        self.next_thread_index = next_thread_index
        self.names = names
        self._index = index
        self.int_thread_count = int_thread_count
        self.all_mask = all_mask
        self.free_mask = free_mask
        self.thread_process = thread_process
        self.process_thread = process_thread
        self.ext = ext

    # -- construction -------------------------------------------------------

    @staticmethod
    def for_test(test: dict) -> "Context":
        """Fresh context: threads 0..concurrency-1 plus "nemesis", all
        free, each running itself (context.clj:258-286)."""
        n = int(test.get("concurrency", 2))
        names = tuple(range(n)) + (NEMESIS,)
        index = {name: i for i, name in enumerate(names)}
        all_mask = (1 << len(names)) - 1
        return Context(
            time=0,
            next_thread_index=0,
            names=names,
            index=index,
            int_thread_count=n,
            all_mask=all_mask,
            free_mask=all_mask,
            thread_process=names,
            process_thread={name: name for name in names},
            ext={},
        )

    def _clone(self, *, time: Any = None, next_thread_index: Any = None,
               all_mask: Any = None, free_mask: Any = None,
               thread_process: Any = None, process_thread: Any = None,
               ext: Any = None) -> "Context":
        # Named parameters, not **kw: this runs ~3x per scheduled op
        # and the kwargs-dict form showed up in whole-stack profiles.
        # None is never a legitimate value for any of these fields, so
        # it doubles as the keep-current sentinel.
        return Context(
            time=self.time if time is None else time,
            next_thread_index=(
                self.next_thread_index if next_thread_index is None
                else next_thread_index
            ),
            names=self.names,
            index=self._index,
            int_thread_count=self.int_thread_count,
            all_mask=self.all_mask if all_mask is None else all_mask,
            free_mask=self.free_mask if free_mask is None else free_mask,
            thread_process=(
                self.thread_process if thread_process is None
                else thread_process
            ),
            process_thread=(
                self.process_thread if process_thread is None
                else process_thread
            ),
            ext=self.ext if ext is None else ext,
        )

    # -- map-ish behavior (context.clj "contexts also behave like maps") ----

    def get(self, k: Any, default: Any = None) -> Any:
        if k == "time":
            return self.time
        return self.ext.get(k, default)

    def assoc(self, k: Any, v: Any) -> "Context":
        if k == "time":
            return self._clone(time=v)
        ext = dict(self.ext)
        ext[k] = v
        return self._clone(ext=ext)

    def with_time(self, time: int) -> "Context":
        return self._clone(time=time)

    # -- thread / process queries ------------------------------------------

    def thread_index(self, thread: Any) -> int:
        return self._index[thread]

    def all_threads(self) -> list:
        return [self.names[i] for i in _mask_bits(self.all_mask)]

    def free_threads(self) -> list:
        return [self.names[i] for i in _mask_bits(self.free_mask)]

    def all_thread_count(self) -> int:
        return self.all_mask.bit_count()

    def free_thread_count(self) -> int:
        return self.free_mask.bit_count()

    def all_processes(self) -> list:
        return [self.thread_process[i] for i in _mask_bits(self.all_mask)]

    def free_processes(self) -> list:
        return [self.thread_process[i] for i in _mask_bits(self.free_mask)]

    def process_to_thread(self, process: Any) -> Any:
        return self.process_thread.get(process)

    def thread_to_process(self, thread: Any) -> Any:
        return self.thread_process[self._index[thread]]

    def thread_free(self, thread: Any) -> bool:
        i = self._index.get(thread)
        return i is not None and bool((self.free_mask >> i) & 1)

    def some_free_process(self) -> Any:
        """A free process, rotating through threads for fairness
        (context.clj:202-218): first free thread at index >=
        next_thread_index, wrapping around."""
        m = self.free_mask >> self.next_thread_index
        if m:
            i = self.next_thread_index + ((m & -m).bit_length() - 1)
            return self.thread_process[i]
        if self.next_thread_index == 0:
            return None
        m = self.free_mask
        if not m:
            return None
        return self.thread_process[(m & -m).bit_length() - 1]

    # -- transitions --------------------------------------------------------

    def busy_thread(self, time: int, thread: Any) -> "Context":
        """Marks thread busy at the given time, and bumps the fairness
        rotation pointer (context.clj:229-238)."""
        i = self._index[thread]
        return self._clone(
            time=time,
            next_thread_index=(self.next_thread_index + 1) % len(self.names),
            free_mask=self.free_mask & ~(1 << i),
        )

    def free_thread(self, time: int, thread: Any) -> "Context":
        i = self._index[thread]
        return self._clone(time=time, free_mask=self.free_mask | (1 << i))

    def with_next_process(self, thread: Any) -> "Context":
        """Replaces a crashed thread's process with a fresh id: old +
        int-thread-count (context.clj:240-256)."""
        i = self._index[thread]
        old = self.thread_process[i]
        if not isinstance(old, int):
            return self
        new = old + self.int_thread_count
        tp = list(self.thread_process)
        tp[i] = new
        pt = dict(self.process_thread)
        pt.pop(old, None)
        pt[new] = thread
        return self._clone(thread_process=tuple(tp), process_thread=pt)

    def __repr__(self) -> str:
        return (
            f"Context(time={self.time}, free={self.free_threads()}, "
            f"all={self.all_threads()})"
        )


def context(test: dict) -> Context:
    return Context.for_test(test)


class AllBut:
    """Predicate matching every thread except one (context.clj:288-307)."""

    __slots__ = ("element",)

    def __init__(self, element: Any):
        self.element = element

    def __call__(self, x: Any) -> bool:
        return x != self.element


def all_but(x: Any) -> AllBut:
    return AllBut(x)


def _as_pred(pred: Any) -> Callable[[Any], bool]:
    if callable(pred) and not isinstance(pred, (set, frozenset)):
        return pred
    s = set(pred) if not isinstance(pred, (set, frozenset)) else pred
    return lambda t: t in s


def make_thread_filter(pred: Any, ctx: Optional[Context] = None):
    """A precompiled context restriction: returns fn(ctx) -> ctx whose
    all/free thread sets are intersected with the threads matching pred
    (context.clj:311-358).  Without a context, compiles lazily on first
    call (thread sets are stable across a run)."""
    p = _as_pred(pred)

    if ctx is None:
        cell: list = [None]

        def lazy(c: Context) -> Context:
            f = cell[0]
            if f is None:
                f = make_thread_filter(p, c)
                cell[0] = f
            return f(c)

        return lazy

    mask = 0
    for i in _mask_bits(ctx.all_mask):
        if p(ctx.names[i]):
            mask |= 1 << i

    def by_mask(c: Context) -> Context:
        return c._clone(
            all_mask=c.all_mask & mask, free_mask=c.free_mask & mask
        )

    return by_mask
