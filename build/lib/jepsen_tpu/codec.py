"""Object <-> bytes serialization for wire payloads.

Equivalent of /root/reference/jepsen/src/jepsen/codec.clj (EDN bytes);
the Python-native data format here is JSON.  None maps to empty bytes
both ways, like the reference's nil."""

from __future__ import annotations

import json
from typing import Any, Optional


def encode(o: Any) -> bytes:
    if o is None:
        return b""
    return json.dumps(o, sort_keys=True).encode()


def decode(data: Optional[bytes]) -> Any:
    if not data:
        return None
    return json.loads(data.decode())
