"""libfaketime wrappers: per-node clock rates for DB binaries.

Equivalent of /root/reference/jepsen/src/jepsen/faketime.clj (:24-47):
instead of skewing the system clock (clock nemesis), wrap a DB binary
in a shell script that runs it under `faketime` with an initial offset
and a rate multiplier, so different nodes experience time passing at
different speeds.  `wrap` moves the real binary aside idempotently;
`unwrap` restores it.
"""

from __future__ import annotations

import random
from typing import Optional

from .control import Session

#: Suffix for the displaced original binary (faketime.clj:37-47).
REAL_SUFFIX = ".no-faketime"


def script(cmd: str, init_offset: float = 0, rate: float = 1.0) -> str:
    """A sh script invoking cmd under faketime (faketime.clj:24-35)."""
    sign = "-" if init_offset < 0 else "+"
    return (
        "#!/bin/bash\n"
        f'faketime -m -f "{sign}{abs(int(init_offset))}s x{float(rate)}" '
        f'{cmd} "$@"\n'
    )


def install(sess: Session) -> None:
    """Installs the faketime binary (the reference builds a patched
    0.9.6 fork; distribution packages are fine for the rate/offset
    features we use)."""
    with sess.su():
        sess.exec_star(
            "env", "DEBIAN_FRONTEND=noninteractive",
            "apt-get", "install", "-y", "faketime",
        )


def _exists(sess: Session, path: str) -> bool:
    return sess.exec_star("test", "-e", path).get("exit") == 0


def wrap(sess: Session, cmd: str, init_offset: float = 0,
         rate: float = 1.0) -> None:
    """Replaces `cmd` with a faketime wrapper, moving the original to
    cmd.no-faketime.  Idempotent (faketime.clj:37-47): re-wrapping just
    rewrites the wrapper script."""
    real = cmd + REAL_SUFFIX
    if not _exists(sess, real):
        sess.exec("mv", cmd, real)
    sess.exec("tee", cmd, stdin=script(real, init_offset, rate))
    sess.exec("chmod", "a+x", cmd)


def unwrap(sess: Session, cmd: str) -> None:
    """Restores the original binary if wrapped (faketime.clj:49-55)."""
    real = cmd + REAL_SUFFIX
    if _exists(sess, real):
        sess.exec("mv", real, cmd)


def rand_factor(factor: float, rng: Optional[random.Random] = None) -> float:
    """A rate drawn around 1 such that max/min = factor
    (faketime.clj:57-66)."""
    rng = rng or random
    hi = 2 / (1 + 1 / factor)
    lo = hi / factor
    return lo + rng.random() * (hi - lo)
