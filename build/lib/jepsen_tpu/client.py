"""Client protocol: how a test talks to the system under test.

Equivalent of /root/reference/jepsen/src/jepsen/client.clj: the `Client`
lifecycle protocol (:9-27 — open!/setup!/invoke!/teardown!/close!), the
`Reusable` marker (:29-34), the `Validate` contract-checking wrapper
(:64-109), and the `Timeout` wrapper (:116-148).

A client instance is bound to one node and (at any moment) one logical
process.  `open` is a factory: given the prototype client from the test
map, produce a fresh connected instance.  The interpreter re-opens
clients whenever a process crashes (interpreter.clj:36-70) unless the
client is `reusable`.
"""

from __future__ import annotations

from typing import Any, Optional

from .history import FAIL, INFO, INVOKE, OK, Op
from .utils import JepsenTimeout, timeout as run_timeout


class Client:
    """DB client lifecycle (client.clj:9-27).

    Subclasses override some or all of: `open` returns a connected copy
    for `node`; `setup` installs any schema/state (once per node, by the
    orchestrator); `invoke` applies an op and returns its completion;
    `teardown` undoes setup; `close` releases the connection."""

    def open(self, test: dict, node: Any) -> "Client":
        return self

    def setup(self, test: dict) -> None:
        pass

    def invoke(self, test: dict, op: Op) -> Op:
        raise NotImplementedError

    def teardown(self, test: dict) -> None:
        pass

    def close(self, test: dict) -> None:
        pass

    def reusable(self, test: dict) -> bool:
        """When true, the interpreter keeps this client across process
        crashes instead of close+open (client.clj:29-34)."""
        return False


class NoopClient(Client):
    """Does nothing, successfully (client.clj:157-161)."""

    def invoke(self, test: dict, op: Op) -> Op:
        return op.complete(OK)

    def reusable(self, test: dict) -> bool:
        return True


noop = NoopClient()


class ValidationError(Exception):
    pass


class Validate(Client):
    """Wraps a client, checking the protocol contract at runtime
    (client.clj:64-109): invoke must return an Op whose type is
    ok/fail/info and whose process and f match the invocation."""

    def __init__(self, client: Client):
        self.client = client

    def open(self, test: dict, node: Any) -> "Validate":
        inner = self.client.open(test, node)
        if inner is None:
            raise ValidationError(
                f"client open returned None instead of a Client "
                f"(from {self.client!r})"
            )
        return Validate(inner)

    def setup(self, test: dict) -> None:
        self.client.setup(test)

    def invoke(self, test: dict, op: Op) -> Op:
        op2 = self.client.invoke(test, op)
        if not isinstance(op2, Op):
            raise ValidationError(
                f"invoke returned {op2!r}, not an Op, for {op!r}"
            )
        problems = []
        if op2.type not in (OK, FAIL, INFO):
            problems.append(f"type must be ok/fail/info, not {op2.type!r}")
        if op2.process != op.process:
            problems.append(
                f"process changed from {op.process!r} to {op2.process!r}"
            )
        if op2.f != op.f:
            problems.append(f"f changed from {op.f!r} to {op2.f!r}")
        if problems:
            raise ValidationError(
                f"invoke of {op!r} returned invalid completion {op2!r}: "
                + "; ".join(problems)
            )
        return op2

    def teardown(self, test: dict) -> None:
        self.client.teardown(test)

    def close(self, test: dict) -> None:
        self.client.close(test)

    def reusable(self, test: dict) -> bool:
        return self.client.reusable(test)


class Timeout(Client):
    """Wraps a client so invocations time out after `ms` milliseconds,
    completing as indeterminate :info ops (client.clj:116-148).  The
    timed-out call keeps running in its daemon thread — same caveat as
    the reference's `util/timeout`."""

    def __init__(self, ms: float, client: Client):
        self.ms = ms
        self.client = client

    def open(self, test: dict, node: Any) -> "Timeout":
        return Timeout(self.ms, self.client.open(test, node))

    def setup(self, test: dict) -> None:
        self.client.setup(test)

    def invoke(self, test: dict, op: Op) -> Op:
        try:
            return run_timeout(self.ms, lambda: self.client.invoke(test, op))
        except JepsenTimeout:
            return op.complete(INFO, error="timeout")

    def teardown(self, test: dict) -> None:
        self.client.teardown(test)

    def close(self, test: dict) -> None:
        self.client.close(test)

    def reusable(self, test: dict) -> bool:
        return self.client.reusable(test)


def timeout(ms: float, client: Client) -> Timeout:
    return Timeout(ms, client)


def validate(client: Client) -> Validate:
    return Validate(client)


def is_op(value: Any) -> bool:
    return isinstance(value, Op)
