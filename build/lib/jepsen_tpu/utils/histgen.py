"""Synthetic concurrent histories for benchmarks and tests.

The reference benchmarks its stack on generated workloads
(/root/reference/jepsen/test/jepsen/core_test.clj:127-132 runs 1e6
list-append ops; interpreter_test.clj:43-88 asserts >10k ops/s) — this
module provides the checker-side analog: concurrent register histories
that are linearizable *by construction* (every op takes effect at one
instant between its invocation and completion), with controllable
concurrency and indeterminate-op rate, plus optional injected
violations.  These drive bench.py and the BASELINE.json 100k-op config.
"""

from __future__ import annotations

import random
from typing import Optional

from ..history.core import History, Op, history


def random_register_history(
    n_ops: int,
    *,
    procs: int = 16,
    info_rate: float = 0.02,
    cas: bool = True,
    n_values: int = 5,
    seed: int = 45100,
    bad: bool = False,
    bad_at: Optional[float] = None,
) -> History:
    """A concurrent cas-register history of ~n_ops operations.

    Each op's effect is applied atomically at completion time, so the
    history is linearizable unless `bad` injects a read of a
    never-written value.  `info_rate` of ops complete as :info
    (indeterminate) — these stay concurrent with everything after them,
    the width driver for WGL search (SURVEY.md §7 "hard parts").  The
    default seed matches the reference's fixed generator-test seed
    (generator/test.clj:48-52)."""
    rng = random.Random(seed)
    value: Optional[int] = None
    ops: list[Op] = []
    # process -> (f, payload, effect_applies) for in-flight ops
    pending: dict[int, tuple] = {}
    started = 0

    def complete(p: int) -> None:
        nonlocal value
        f, payload, as_info = pending.pop(p)
        if as_info:
            # Indeterminate: maybe the effect happened.
            if f == "write" and rng.random() < 0.5:
                value = payload
            elif f == "cas" and rng.random() < 0.5 and value == payload[0]:
                value = payload[1]
            ops.append(Op(type="info", f=f, value=payload, process=p))
            return
        if f == "read":
            ops.append(Op(type="ok", f="read", value=value, process=p))
        elif f == "write":
            value = payload
            ops.append(Op(type="ok", f="write", value=payload, process=p))
        else:  # cas
            if value == payload[0]:
                value = payload[1]
                ops.append(Op(type="ok", f="cas", value=payload, process=p))
            else:
                ops.append(Op(type="fail", f="cas", value=payload, process=p))

    while started < n_ops or pending:
        p = rng.randrange(procs)
        if p in pending:
            complete(p)
        elif started < n_ops:
            fs = ["read", "write", "cas"] if cas else ["read", "write"]
            f = rng.choice(fs)
            if f == "read":
                payload = None
            elif f == "write":
                payload = rng.randrange(n_values)
            else:
                payload = (rng.randrange(n_values), rng.randrange(n_values))
            as_info = f != "read" and rng.random() < info_rate
            pending[p] = (f, payload, as_info)
            ops.append(Op(type="invoke", f=f, value=payload, process=p))
            started += 1
        # else: only pending ops remain; loop drains them.

    if bad:
        ops.append(Op(type="invoke", f="read", value=None, process=0))
        ops.append(Op(type="ok", f="read", value=n_values + 94, process=0))
    if bad_at is not None:
        # A mid-history impossible read (a value no op ever writes), on
        # a process id outside the worker range so it can't collide
        # with an in-flight op.  Unlike `bad`, the violation sits at
        # `bad_at` of the way through: a search in event order has to
        # chew through everything before it — info-op width and all —
        # before the infeasibility is reachable, which is the shape
        # that breaks beam-capped device BFS (VERDICT r2 "missing" #2).
        at = max(0, min(len(ops), int(bad_at * len(ops))))
        ops[at:at] = [
            Op(type="invoke", f="read", value=None, process=procs),
            Op(type="ok", f="read", value=n_values + 73, process=procs),
        ]
    return history(ops)


def stale_read_history(
    n_ops: int,
    *,
    procs: int = 16,
    info_rate: float = 0.05,
    n_values: int = 5,
    seed: int = 45100,
    read_at: float = 0.6,
) -> History:
    """A concurrent register history that is genuinely non-linearizable
    through the async-replication shape (the repkv violation,
    suites/repkv.py): a value S is written and acknowledged early, an
    acknowledged fence write overwrites it, and much later a read still
    returns S.  Every producer of S completes before the fence begins
    and the fence completes before the read is invoked, so no
    linearization order can serve S to the read — the proof obligation
    checker/refute.py's stale-read screen discharges at any scale.

    The body between fence and read is an ordinary linearizable-by-
    construction workload (values 0..n_values-1 < S, so nothing
    re-produces S; info ops welcome)."""
    S = n_values  # retired value: body ops can never produce it
    prologue = [
        Op(type="invoke", f="write", value=S, process=0),
        Op(type="ok", f="write", value=S, process=0),
        # fence: acknowledged overwrite, window disjoint from both the
        # producer above and the stale read below
        Op(type="invoke", f="write", value=0, process=0),
        Op(type="ok", f="write", value=0, process=0),
    ]
    body = list(
        random_register_history(
            n_ops - 3, procs=procs, info_rate=info_rate,
            n_values=n_values, seed=seed,
        )
    )
    at = max(0, min(len(body), int(read_at * len(body))))
    body[at:at] = [
        Op(type="invoke", f="read", value=None, process=procs),
        Op(type="ok", f="read", value=S, process=procs),
    ]
    return history(prologue + body)
